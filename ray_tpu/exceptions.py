"""User-facing error types.

Reference semantics: python/ray/exceptions.py — errors are themselves
objects: a failed task's return object *contains* the error, so it
propagates through dependency chains (TaskError wrapping) and surfaces at
``get`` time.
"""

from __future__ import annotations

import pickle
import traceback


class RayTpuError(Exception):
    """Base for all framework errors."""


def _format_context(context) -> str:
    """``" [k=v k2=v2]"`` suffix for FT error messages, or "".

    A ``last_logs`` key (the death report's final log excerpt) renders
    as an indented block after the suffix instead of inline — five log
    lines crammed into the bracket would bury the cause fields
    (``signal=``, ``oom=``, ``postmortem=``) they accompany."""
    if not context:
        return ""
    ctx = dict(context)
    last_logs = ctx.pop("last_logs", None)
    parts = []
    for k, v in ctx.items():
        if isinstance(v, bytes):
            v = v.hex()[:16]
        parts.append(f"{k}={v}")
    out = " [" + " ".join(parts) + "]" if parts else ""
    if last_logs:
        out += "\n  last logs from the dead process:"
        for line in list(last_logs)[-5:]:
            out += f"\n    {str(line)[:300]}"
    return out


def _picklable_cause(cause: BaseException) -> BaseException:
    """Return ``cause`` if it survives a pickle round-trip, else a
    stringified stand-in.  Errors cross the RPC boundary inside task
    results; an unpicklable user exception must degrade gracefully
    rather than kill the connection's reader."""
    try:
        pickle.loads(pickle.dumps(cause))
        return cause
    except Exception:
        return RayTpuError(f"{type(cause).__name__}: {cause}")


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ``get`` of its outputs.

    Mirrors RayTaskError (python/ray/exceptions.py) including cause
    chaining: if a task fails because an *argument* holds a TaskError,
    the original error is propagated unwrapped.

    Custom ``__init__`` signatures break the default ``Exception``
    reduce (it replays ``cls(*args)`` with ``args`` = the message), so
    every exception here with extra fields defines ``__reduce__``.
    """

    def __init__(self, function_name: str, cause: BaseException,
                 tb_str: str | None = None):
        self.function_name = function_name
        self.tb_str = tb_str or "".join(
            traceback.format_exception(type(cause), cause, cause.__traceback__)
        )
        self.cause = cause
        super().__init__(f"task {function_name} failed: {cause!r}")

    def __reduce__(self):
        # Sanitize lazily: local (non-cluster) consumers keep the real
        # cause object; only the wire copy degrades to a stand-in.
        return (type(self),
                (self.function_name, _picklable_cause(self.cause),
                 self.tb_str))

    def __str__(self):
        return (f"{type(self.cause).__name__} in task {self.function_name}\n"
                f"{self.tb_str}")


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead (creation failed, killed, or out of restarts).

    Carries structured context so the message is actionable at the
    driver: ``node_id`` (where it was hosted) and a free-form
    ``context`` dict the failure site fills in (pass/step index,
    originating channel edge, chaos detail, ...)."""

    def __init__(self, actor_id=None, reason: str = "actor died",
                 node_id=None, context=None):
        self.actor_id = actor_id
        self.reason = reason
        self.node_id = node_id
        self.context = dict(context or {})
        ctx = dict(self.context)
        if actor_id is not None:
            hexfn = getattr(actor_id, "hex", None)
            ctx.setdefault("actor_id",
                           hexfn()[:16] if callable(hexfn) else actor_id)
        if node_id is not None:
            ctx.setdefault("node_id", str(node_id)[:16])
        super().__init__(reason + _format_context(ctx))

    def __reduce__(self):
        return (type(self), (self.actor_id, self.reason, self.node_id,
                             self.context))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object value unrecoverable (all copies lost, lineage exhausted).

    ``context`` mirrors ActorDiedError: holder node, originating edge,
    pass index — whatever the failure site knows."""

    def __init__(self, object_ref=None, reason: str = "object lost",
                 context=None):
        self.object_ref = object_ref
        self.reason = reason
        self.context = dict(context or {})
        super().__init__(reason + _format_context(self.context))

    def __reduce__(self):
        return (type(self), (self.object_ref, self.reason, self.context))


class ChannelError(RayTpuError):
    """A channel-data-plane edge failed: the producer feeding the ring
    raised (its error frame rides here as ``__cause__``), the ring was
    severed/closed mid-pass, or the read deadline expired.  ``context``
    names the edge (ring path, producer actor, frame/pass index) so the
    driver-side message is actionable.  Propagates UNWRAPPED through
    task results (like the other FT errors) so callers can catch it
    typed."""

    def __init__(self, reason: str = "channel error", context=None):
        self.reason = reason
        self.context = dict(context or {})
        super().__init__(reason + _format_context(self.context))

    def __reduce__(self):
        return (type(self), (self.reason, self.context))


class ShuffleError(RayTpuError):
    """A push-based exchange (data/exchange.py) failed as a whole: a
    map task died mid-shuffle, pushed fragments never landed at their
    reducers within the deadline, or a reducer actor was lost.  The
    exchange tears its reducers/rings down BEFORE raising, so a failed
    shuffle never leaves hung reader threads behind.  ``context`` names
    the exchange (op, shuffle id, expected/received fragment counts)."""

    def __init__(self, reason: str = "shuffle failed", context=None):
        self.reason = reason
        self.context = dict(context or {})
        super().__init__(reason + _format_context(self.context))

    def __reduce__(self):
        return (type(self), (self.reason, self.context))


class ZipLengthMismatchError(RayTpuError, ValueError):
    """``Dataset.zip`` requires equal row counts; raised driver-side
    from the metadata round, before any block moves."""

    def __init__(self, left_rows: int, right_rows: int):
        self.left_rows = int(left_rows)
        self.right_rows = int(right_rows)
        super().__init__(
            f"Dataset.zip requires equal row counts: left has "
            f"{self.left_rows} rows, right has {self.right_rows}")

    def __reduce__(self):
        return (type(self), (self.left_rows, self.right_rows))


class UnionSchemaError(RayTpuError, TypeError):
    """``Dataset.union`` requires every source to share one column
    set; raised from the schema probe before blocks interleave."""

    def __init__(self, left_schema, right_schema):
        self.left_schema = sorted(left_schema)
        self.right_schema = sorted(right_schema)
        super().__init__(
            f"Dataset.union sources disagree on columns: "
            f"{self.left_schema} vs {self.right_schema}")

    def __reduce__(self):
        return (type(self), (self.left_schema, self.right_schema))


class ObjectFreedError(ObjectLostError):
    """Object was explicitly freed by the application."""


class OwnerDiedError(ObjectLostError):
    """The object's owner process died; value and lineage are gone."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__("task was cancelled")

    def __reduce__(self):
        return (type(self), (self.task_id,))


class PendingCallsLimitExceededError(RayTpuError):
    """Actor's max_pending_calls exceeded — the bounded-mailbox
    admission signal.  Serve's router treats it as *route elsewhere*
    (the replica is saturated, not broken); bare actor callers see it
    raised at submission."""


class BackPressureError(RayTpuError):
    """Request rejected by admission control: a bounded queue (replica
    mailbox, ``@serve.batch`` queue, router with every replica
    saturated) is full.  Deliberately a REJECTION, not a failure — the
    work was never started, so the caller may safely retry after
    ``retry_after_s`` (the HTTP proxy maps this to 503 + Retry-After,
    the gRPC proxy to UNAVAILABLE)."""

    def __init__(self, reason: str = "request rejected: queue full",
                 retry_after_s: float | None = None, context=None):
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.context = dict(context or {})
        ctx = dict(self.context)
        if retry_after_s is not None:
            ctx.setdefault("retry_after_s", round(retry_after_s, 3))
        super().__init__(reason + _format_context(ctx))

    def __reduce__(self):
        return (type(self), (self.reason, self.retry_after_s,
                             self.context))


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's end-to-end deadline expired.  Raised both when
    already-expired work is SHED before execution (scheduler dispatch,
    actor mailbox dequeue, batch flush — user code never ran) and when
    a caller's ``get``/``result`` budget runs out while the work is
    still in flight.  ``context`` names the shed point (``where``) and
    how late the work was (``late_by_s``)."""

    def __init__(self, reason: str = "deadline exceeded",
                 deadline: float | None = None, context=None):
        self.reason = reason
        self.deadline = deadline
        self.context = dict(context or {})
        super().__init__(reason + _format_context(self.context))

    def __reduce__(self):
        return (type(self), (self.reason, self.deadline, self.context))


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` exceeded its timeout."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing a worker's runtime environment failed."""


class NodeDiedError(RayTpuError):
    pass


class StaleEpochError(RayTpuError):
    """A mutating control-plane RPC carried a fenced lease epoch.

    The head minted the caller a ``(lease_id, epoch)`` pair at node
    registration; declaring the node dead fences that epoch, and a
    later re-registration mints a strictly newer one.  A write arriving
    with a superseded epoch is a ZOMBIE — a node that was declared dead
    and never re-attached (partition heal, paused VM, delayed packet) —
    and is rejected typed instead of silently overwriting live state
    (the classic lease-fencing pattern; reference: GCS node-death
    fencing via raylet restarts + the fencing-token literature).

    The fix on the caller's side is always the same: re-register (the
    heartbeat loop does this automatically on its next beat) and replay
    intent against the CURRENT cluster state, which may have moved on.
    """

    def __init__(self, reason: str = "stale lease epoch", *,
                 node_id: str = "", sent_epoch=None,
                 current_epoch=None, context=None):
        self.reason = reason
        self.node_id = node_id
        self.sent_epoch = sent_epoch
        self.current_epoch = current_epoch
        self.context = dict(context or {})
        ctx = dict(self.context)
        if node_id:
            ctx.setdefault("node_id", node_id[:12])
        if sent_epoch is not None:
            ctx.setdefault("sent_epoch", sent_epoch)
        if current_epoch is not None:
            ctx.setdefault("current_epoch", current_epoch)
        super().__init__(reason + _format_context(ctx))

    def __reduce__(self):
        return (_rebuild_stale_epoch,
                (self.reason, self.node_id, self.sent_epoch,
                 self.current_epoch, self.context))


def _rebuild_stale_epoch(reason, node_id, sent, cur, context):
    return StaleEpochError(reason, node_id=node_id, sent_epoch=sent,
                           current_epoch=cur, context=context)


class NotPrimaryError(StaleEpochError):
    """A mutating control-plane RPC reached a head that is not the
    current primary — a standby still tailing the journal, or a
    DEPOSED primary fenced by a newer head generation after failover.

    Subclasses :class:`StaleEpochError` because the contract is the
    same lease-fencing contract one level up: head *generations* are
    fencing tokens minted at promotion, exactly as node epochs are
    minted at registration.  A write acked by a deposed primary would
    be a zombie write at cluster scope, so it is rejected typed before
    it can land.

    ``generation`` is the rejecting head's generation;
    ``primary_hint`` (may be "") is the address that head believes is
    the current primary — clients use it to re-resolve their head set
    (``ClusterClient.mut_call`` fails over and retries).
    """

    def __init__(self, reason: str = "head is not primary", *,
                 generation: int = 0, primary_hint: str = "",
                 context=None):
        self.generation = int(generation)
        self.primary_hint = primary_hint
        ctx = dict(context or {})
        ctx.setdefault("head_gen", self.generation)
        if primary_hint:
            ctx.setdefault("primary_hint", primary_hint)
        super().__init__(reason, context=ctx)

    def __reduce__(self):
        return (_rebuild_not_primary,
                (self.reason, self.generation, self.primary_hint,
                 self.context))


def _rebuild_not_primary(reason, generation, primary_hint, context):
    return NotPrimaryError(reason, generation=generation,
                           primary_hint=primary_hint, context=context)


class OutOfMemoryError(RayTpuError):
    """Worker killed by the memory monitor (reference: OOM killer, N22)."""


class WorkerCrashedError(RayTpuError):
    """An isolated worker subprocess died mid-task (segfault, os._exit,
    external kill).  A system failure: retried within max_retries
    (reference: worker process death → task retry)."""
