"""Lazy DAG construction over tasks/actors.

Reference semantics: python/ray/dag/dag_node.py — ``fn.bind(...)`` builds
a DAGNode instead of submitting; ``dag.execute(input)`` walks the graph
submitting tasks/actor calls with parent outputs as ObjectRef args.
``experimental_compile`` (compiled graphs / aDAG, dag_node.py:184) is the
static-schedule fast path; here it maps to the channel-based executor in
ray_tpu.dag.compiled (built on mutable-object channels + ICI p2p for
jax arrays) once that lands — bind/execute works today.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: a lazily-bound call whose args may contain other DAGNodes."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal -----------------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)

        for a in self._bound_args:
            scan(a)
        for v in self._bound_kwargs.values():
            scan(v)
        return out

    def _resolve_args(self, cache: Dict[int, Any], input_value):
        args = tuple(
            a._execute_impl(cache, input_value) if isinstance(a, DAGNode)
            else a
            for a in self._bound_args)
        kwargs = {
            k: (v._execute_impl(cache, input_value) if isinstance(v, DAGNode)
                else v)
            for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_impl(self, cache: Dict[int, Any], input_value):
        key = id(self)
        if key not in cache:
            cache[key] = self._submit(cache, input_value)
        return cache[key]

    def _submit(self, cache, input_value):
        raise NotImplementedError

    def execute(self, *input_values):
        """Run the DAG; returns ObjectRef(s) for the terminal node(s)."""
        input_value = input_values[0] if input_values else None
        return self._execute_impl({}, input_value)

    def experimental_compile(self, **kwargs):
        from .compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """Placeholder bound to the value passed to ``execute``. Usable as a
    context manager for parity with the reference API:

        with InputNode() as inp:
            dag = f.bind(inp)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, cache, input_value):
        return input_value


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs,
                 options: Optional[Dict[str, Any]] = None):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn
        self._options = options or {}

    def _submit(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        handle = (self._remote_fn.options(**self._options)
                  if self._options else self._remote_fn)
        return handle.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """Actor-creation node; attribute access yields method nodes."""

    def __init__(self, actor_class, args, kwargs,
                 options: Optional[Dict[str, Any]] = None):
        super().__init__(args, kwargs)
        self._actor_class = actor_class
        self._options = options or {}
        self._handle_lock = threading.Lock()
        self._handle = None

    def _get_or_create_handle(self, cache, input_value):
        with self._handle_lock:
            if self._handle is None:
                args, kwargs = self._resolve_args(cache, input_value)
                cls = (self._actor_class.options(**self._options)
                       if self._options else self._actor_class)
                self._handle = cls.remote(*args, **kwargs)
            return self._handle

    def _execute_impl(self, cache, input_value):
        return self._get_or_create_handle(cache, input_value)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundMethod(self, name)


class _UnboundMethod:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name, args,
                               kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, target, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._target = target  # ActorHandle or ClassNode
        self._method_name = method_name

    def _submit(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        if isinstance(self._target, ClassNode):
            handle = self._target._get_or_create_handle(cache, input_value)
        else:
            handle = self._target
        return getattr(handle, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Terminal node aggregating several outputs into a list of refs."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _submit(self, cache, input_value):
        return [o._execute_impl(cache, input_value)
                for o in self._bound_args]
