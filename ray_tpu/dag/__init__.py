from .dag_node import (ClassMethodNode, ClassNode, DAGNode, FunctionNode,
                       InputNode, MultiOutputNode)

__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "InputNode", "MultiOutputNode"]
