"""Compiled DAG execution (aDAG equivalent).

Reference semantics: python/ray/dag/compiled_dag_node.py:691 — a bound
DAG compiles once into a static schedule over pre-resolved endpoints
with pre-allocated channels, replacing per-call graph interpretation.

What compiling buys here (TPU-first reading of the same idea):
- The graph is FLATTENED ONCE into a slot-indexed step plan: per
  execute there is no DAG traversal, no per-node dict building, no
  re-binding — each step is (endpoint, arg-slot template).
- DAG actors are created eagerly at compile time with their endpoints
  pre-resolved into the plan (the reference's per-actor execution
  loops); constructor args must be static.
- Executions PIPELINE: ``execute`` returns refs immediately and up to
  ``max_in_flight`` executions overlap (submission backpressure via
  completion callbacks) — the aDAG property that lets a pipeline
  schedule keep every stage busy.
- Same-host actor→actor edges ride the NATIVE CHANNEL data plane
  (experimental.channel over native/channel.cc): compile pre-plans one
  shm ring per edge, steady-state passes move payloads writer→reader
  at memcpy speed with no object minting, no reference-counting
  traffic.  Rings are sized from the first pass (or the
  ``channel_slot_bytes`` option); an oversized payload falls back to
  the object plane per-pass without breaking the plan.  Cross-host,
  driver-facing, and non-actor edges keep riding the object plane:
  in-process consumers share sealed values zero-copy; cross-node
  consumers pull primary copies over the chunk protocol.  (jax arrays
  additionally move device-to-device only at true process boundaries.)

Options (``experimental_compile(**kw)``): ``channel_transport=True``
(auto-off when the native lib cannot build), ``channel_slots`` (ring
depth, default tracks ``max_in_flight``), ``channel_slot_bytes`` (slot
size hint; default sizes from the first pass), ``channel_timeout``.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from .dag_node import (ClassMethodNode, ClassNode, DAGNode, FunctionNode,
                       InputNode, MultiOutputNode)
from ..observability import tracing as _tracing

_NULL_CTX = contextlib.nullcontext()
_log = logging.getLogger("ray_tpu.dag")

def _dag_metrics():
    """Compiled-DAG pass/recovery series (rebuilt after registry
    resets)."""
    from ..observability import metrics as _metrics

    return _metrics.metric_group("dag", lambda: {
        "passes": _metrics.Counter(
            "ray_tpu_dag_passes_total", "compiled-DAG passes submitted"),
        "pass_failures": _metrics.Counter(
            "ray_tpu_dag_pass_failures_total",
            "passes completed with a fault-tolerance error "
            "(ring fault, dead actor, lost object)"),
        "replans": _metrics.Counter(
            "ray_tpu_dag_replans_total",
            "ring-plan rebuilds after restarts/data-plane faults"),
        "pass_seconds": _metrics.Histogram(
            "ray_tpu_dag_pass_seconds",
            "submit→last-output-complete latency per pass",
            boundaries=[0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0]),
    })


class _Step:
    __slots__ = ("submit", "arg_plan", "kw_plan", "out_slot", "node")

    def __init__(self, submit, arg_plan, kw_plan, out_slot, node=None):
        self.submit = submit      # fn(*args, **kwargs) -> ref
        self.arg_plan = arg_plan  # [("const", v) | ("slot", i) | ("input",)]
        self.kw_plan = kw_plan    # {k: same}
        self.out_slot = out_slot
        self.node = node          # source DAGNode (channel planning)


class CompiledDAG:
    def __init__(self, root: DAGNode, max_in_flight: int = 8,
                 **_options):
        self._root = root
        self._max_in_flight = max(1, max_in_flight)
        self._in_flight = threading.Semaphore(self._max_in_flight)
        self._options = _options
        self._slots_of: Dict[int, int] = {}
        self._steps: List[_Step] = []
        self._multi_output: Optional[List[int]] = None
        # Channel data plane: ring path per same-host actor edge
        # (producer_step, consumer_step) -> path; torn down with us.
        self._channel_edges: Dict[Tuple[int, int], str] = {}
        # path -> endpoint-hosting node addresses (None = this
        # process); teardown reaches remote rings through these.
        self._channel_nodes: Dict[str, set] = {}
        # Restart-aware re-planning state: the pristine (object-plane)
        # per-step plans, restart counts of channel actors at plan
        # time, and a dirty flag set by failures / head actor-state
        # events.  A dirty plan is torn down and rebuilt against the
        # actors' CURRENT endpoints at the next execute; an actor still
        # RESTARTING at that point simply yields no ring (its edges
        # fall back to the object plane) until a later replan.
        self._plane_plans: Optional[List[Tuple]] = None
        self._chan_recovery = False
        self._chan_restarts: Dict[Any, int] = {}
        self._chan_actor_bytes: set = set()
        self._rings_dirty = False
        self._state_listener = None
        self._submit_order_lock = threading.Lock()
        # (class_node, handle): teardown kills AND clears the node's
        # cached handle so a recompile makes a fresh actor.
        self._actors: List[Tuple[Any, Any]] = []
        # Refs of in-flight executions: held until completion so a
        # fire-and-forget caller can't free the tail object before its
        # callback fires (a freed object drops pending callbacks and
        # would leak the semaphore slot).
        self._holding: set = set()
        try:
            self._compile(root)
        except BaseException:
            # A failed compile must not leak the actors it already
            # created (there is no CompiledDAG object to teardown).
            self.teardown()
            raise

    # ------------------------------------------------------------ compile
    def _compile(self, root: DAGNode):
        order: List[DAGNode] = []
        seen: Dict[int, bool] = {}

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen[id(node)] = True
            for child in node._children():
                visit(child)
            order.append(node)

        visit(root)

        for node in order:
            if isinstance(node, InputNode):
                continue
            if isinstance(node, ClassNode):
                self._ensure_actor(node)
                continue
            if isinstance(node, MultiOutputNode):
                self._multi_output = [
                    self._plan_entry(o) for o in node._bound_args]
                continue
            arg_plan = [self._plan_entry(a) for a in node._bound_args]
            kw_plan = {k: self._plan_entry(v)
                       for k, v in node._bound_kwargs.items()}
            out_slot = len(self._slots_of)
            self._slots_of[id(node)] = out_slot
            self._steps.append(_Step(
                self._make_submit(node), arg_plan, kw_plan, out_slot,
                node=node))
        self._plan_channel_transport()

    def _plan_entry(self, v) -> Tuple:
        if isinstance(v, InputNode):
            return ("input",)
        if isinstance(v, ClassNode):
            # An actor handle passed as a task argument resolves to the
            # compile-time actor (same as the interpreted path).
            return ("const", self._ensure_actor(v))
        if isinstance(v, DAGNode):
            slot = self._slots_of.get(id(v))
            if slot is None:
                raise ValueError(
                    "DAG argument is not in topological order "
                    "(unsupported node kind in compiled mode?)")
            return ("slot", slot)
        return ("const", v)

    def _static_args(self, node: DAGNode):
        for a in list(node._bound_args) + \
                list(node._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                raise ValueError(
                    "compiled DAG actors must have static constructor "
                    "args (reference aDAG constraint)")
        return node._bound_args, node._bound_kwargs

    def _ensure_actor(self, node: ClassNode):
        """Long-lived DAG actor, created once at compile
        (compiled_dag_node.py: actors own their loops).  Constructor
        args must be static.  ClassNodes are only reachable through
        their method nodes' _target (not _children), so creation is
        on demand, under the node's own handle lock (the interpreted
        path shares it)."""
        with node._handle_lock:
            if node._handle is None:
                args, kwargs = self._static_args(node)
                cls = (node._actor_class.options(**node._options)
                       if node._options else node._actor_class)
                node._handle = cls.remote(*args, **kwargs)
                self._actors.append((node, node._handle))
            return node._handle

    def _make_submit(self, node: DAGNode):
        if isinstance(node, FunctionNode):
            handle = (node._remote_fn.options(**node._options)
                      if node._options else node._remote_fn)
            return handle.remote
        if isinstance(node, ClassMethodNode):
            target = node._target
            if isinstance(target, ClassNode):
                actor = self._ensure_actor(target)
            else:
                actor = target
            return getattr(actor, node._method_name).remote
        raise TypeError(f"cannot compile node {type(node).__name__}")

    # ----------------------------------------------------- channel plan
    def _chan_actor(self, node, loc_cache):
        """(handle, host_key, node_address) if this step can terminate
        a channel edge, else None.  Two steps with EQUAL host keys
        share a /dev/shm namespace (same machine), so the edge between
        them may ride a ring — including edges between two actors that
        are both remote to the driver but co-located.  node_address
        (None = this process) is kept so teardown can reach the ring's
        hosting process."""
        from ..experimental.channel import channel_location

        if not isinstance(node, ClassMethodNode):
            return None
        target = node._target
        handle = (self._ensure_actor(target)
                  if isinstance(target, ClassNode) else target)
        actor_id = getattr(handle, "_actor_id", None)
        if actor_id is None:
            return None
        if actor_id not in loc_cache:
            loc_cache[actor_id] = channel_location(handle)
        loc = loc_cache[actor_id]
        return (handle,) + loc if loc is not None else None

    def _plan_channel_transport(self):
        """Pre-allocate one shm ring per same-host actor→actor edge and
        rewrite those steps onto the channel trampoline.  Everything
        not eligible (cross-host actors, plain tasks, driver-facing
        outputs) keeps the object-plane plan untouched."""
        if not self._steps or not self._options.get(
                "channel_transport", True):
            return
        from ..experimental import channel as chx

        if not chx.channels_available():
            return
        # Pristine object-plane plans, for restart-driven re-planning
        # (snapshot once; replans restore from here before re-running
        # this method).
        if self._plane_plans is None:
            self._plane_plans = [
                (list(s.arg_plan), dict(s.kw_plan), s.submit)
                for s in self._steps]
        loc_cache: Dict[Any, Any] = {}
        actor_of = [self._chan_actor(s.node, loc_cache)
                    for s in self._steps]
        self._snapshot_chan_actors(actor_of)

        # Driver-facing outputs must come back as object-plane values.
        if self._multi_output is not None:
            terminal = {e[1] for e in self._multi_output
                        if e[0] == "slot"}
        else:
            terminal = {len(self._steps) - 1}

        n_slots = int(self._options.get("channel_slots", 0)) or \
            max(2, self._max_in_flight)
        hint = int(self._options.get("channel_slot_bytes", 0))
        timeout = float(self._options.get(
            "channel_timeout", chx.DEFAULT_TIMEOUT_S))

        # Edge discovery: (producer_step, consumer_step) once per pair
        # (a consumer using the same output twice consumes ONE frame).
        plane_consumers: set = set()   # producers with an object-plane consumer
        for c_idx, step in enumerate(self._steps):
            for e in list(step.arg_plan) + list(step.kw_plan.values()):
                if e[0] != "slot":
                    continue
                p_idx = e[1]
                if actor_of[c_idx] is not None \
                        and actor_of[p_idx] is not None \
                        and actor_of[c_idx][1] == actor_of[p_idx][1]:
                    path = self._channel_edges.setdefault(
                        (p_idx, c_idx),
                        chx.channel_path(f"dag{p_idx}-{c_idx}"))
                    # Endpoint-hosting nodes, for teardown (None =
                    # this process).
                    self._channel_nodes.setdefault(path, set()).update(
                        (actor_of[p_idx][2], actor_of[c_idx][2]))
                else:
                    plane_consumers.add(p_idx)
        if not self._channel_edges:
            return

        writes_of: Dict[int, list] = {}
        for (p, c), path in self._channel_edges.items():
            writes_of.setdefault(p, []).append(
                chx.writer_spec(path, n_slots, hint, timeout))

        for c_idx, step in enumerate(self._steps):
            def rewrite(e, c_idx=c_idx):
                if e[0] == "slot" and (e[1], c_idx) in self._channel_edges:
                    # The producer's actor id rides the marker so the
                    # reader can probe its liveness while blocked.
                    producer = getattr(actor_of[e[1]][0],
                                       "_actor_id", None)
                    return ("const", chx.ChannelArg(
                        self._channel_edges[(e[1], c_idx)], timeout,
                        producer=producer))
                return e

            step.arg_plan = [rewrite(e) for e in step.arg_plan]
            step.kw_plan = {k: rewrite(e)
                            for k, e in step.kw_plan.items()}

        producers = {p for (p, _c) in self._channel_edges}
        consumers = {c for (_p, c) in self._channel_edges}
        for idx in producers | consumers:
            step = self._steps[idx]
            # A pure channel producer returns a token, not the payload;
            # anything the driver or an object-plane consumer reads
            # still comes back as a value.
            returns_value = (idx in terminal or idx in plane_consumers
                             or idx not in producers)
            step.submit = self._make_channel_submit(
                step.node, tuple(writes_of.get(idx, ())), returns_value)
        self._chan_recovery = True
        self._subscribe_actor_state()

    # -------------------------------------------------- channel recovery
    def _snapshot_chan_actors(self, actor_of):
        """Record restart counts (local actors) and binary ids (for
        head actor-state events) of every channel-capable actor, so
        later executes can detect a restart and re-plan.  MERGES into
        the existing tracking: a replan that runs while an actor is
        mid-restart sees it as channel-incapable (not ALIVE), and
        dropping it here would mean its later ALIVE event could never
        mark the plan dirty again — its edges would silently ride the
        object plane forever."""
        from ..core.runtime import try_get_runtime

        rt = try_get_runtime()
        for entry in actor_of:
            if entry is None:
                continue
            aid = getattr(entry[0], "_actor_id", None)
            if aid is None:
                continue
            self._chan_actor_bytes.add(aid.binary())
            if rt is not None:
                self._chan_restarts[aid] = \
                    rt.actor_manager.num_restarts(aid)

    def _subscribe_actor_state(self):
        """Cluster mode: head-published actor FSM transitions for our
        channel actors mark the ring plan dirty (RESTARTING → tear
        down, fall back to the object plane; ALIVE → rebuild against
        the new endpoints)."""
        from ..core.runtime import try_get_runtime

        rt = try_get_runtime()
        if (rt is None or rt.cluster is None
                or self._state_listener is not None):
            return

        def on_state(aid_bytes, _state, _event):
            if aid_bytes in self._chan_actor_bytes:
                self._rings_dirty = True

        self._state_listener = on_state
        rt.cluster.add_actor_state_listener(on_state)

    def _restarts_changed(self) -> bool:
        from ..core.runtime import try_get_runtime

        rt = try_get_runtime()
        if rt is None:
            return False
        return any(rt.actor_manager.num_restarts(aid) != n
                   for aid, n in self._chan_restarts.items())

    @staticmethod
    def _record_pass_failure(err) -> None:
        """Drop a timeline instant for a pass that died to an FT error
        so a postmortem merge shows WHERE in the DAG the pass failed,
        not just that an actor exited.  last_logs stays out: the
        timeline plane ships to the head and the log excerpt already
        rides the death report itself."""
        try:
            from ..observability import timeline as _timeline

            ctx = dict(getattr(err, "context", None) or {})
            ctx.pop("last_logs", None)
            _timeline.record_event(
                "dag:pass-failure", "i", pid=_timeline.process_pid(),
                args={"error": type(err).__name__, **ctx})
        except Exception:
            pass

    def _maybe_replan(self):
        """Called under _submit_order_lock at the top of execute: when
        a channel actor restarted (or a pass died to a ring fault),
        tear down the stale rings — waking anything still blocked on
        them — restore the pristine object-plane plans, and re-run
        channel planning against the actors' CURRENT endpoints.  An
        actor still mid-restart contributes no ring this round (its
        edges ride the object plane) and triggers another replan when
        its ALIVE event lands."""
        if not self._chan_recovery:
            return
        if not (self._rings_dirty or self._restarts_changed()):
            return
        _dag_metrics()["replans"].inc()
        from ..experimental.channel import (destroy_channel,
                                            destroy_channel_at)

        old_edges = dict(self._channel_edges)
        old_nodes = dict(self._channel_nodes)
        for step, (ap, kp, sub) in zip(self._steps, self._plane_plans):
            step.arg_plan = list(ap)
            step.kw_plan = dict(kp)
            step.submit = sub
        self._channel_edges = {}
        self._channel_nodes = {}
        self._rings_dirty = False
        # Local teardown inline (fast; wakes blocked local endpoints).
        # The REMOTE destroys ride a background thread: RPCs against a
        # possibly-dead node cost seconds each, and we are under
        # _submit_order_lock — concurrent execute() callers must not
        # stall behind the teardown ("the lock is held briefly").
        for path in old_edges.values():
            destroy_channel(path)
        remote_nodes = {path: nodes for path in old_edges.values()
                        if (nodes := {a for a in
                                      old_nodes.get(path, ()) if a})}
        if remote_nodes:
            threading.Thread(
                target=lambda: [destroy_channel_at(p, ns)
                                for p, ns in remote_nodes.items()],
                daemon=True,
                name="dag-ring-teardown").start()
        self._plan_channel_transport()

    def _make_channel_submit(self, node, writes, returns_value):
        from ..experimental.channel import submit_channel_call

        target = node._target
        handle = (self._ensure_actor(target)
                  if isinstance(target, ClassNode) else target)
        method = node._method_name

        def submit(*args, **kwargs):
            return submit_channel_call(
                handle, method, args, kwargs, writes=writes,
                returns_value=returns_value)

        return submit

    # ------------------------------------------------------------ execute
    def execute(self, *input_values) -> Any:
        """Run one pass over the static plan; returns the terminal
        ref(s) immediately.  Up to ``max_in_flight`` passes overlap."""
        import time as _time

        input_value = input_values[0] if input_values else None
        self._in_flight.acquire()
        released = [False]
        rel_lock = threading.Lock()
        t_pass0 = _time.perf_counter()
        _dag_metrics()["passes"].inc()

        def release_all(refs):
            with rel_lock:
                if released[0]:
                    return
                released[0] = True
            if refs:
                _dag_metrics()["pass_seconds"].observe(
                    _time.perf_counter() - t_pass0)
            for r in refs:
                self._holding.discard(r)
            self._in_flight.release()

        try:
            slots: List[Any] = [None] * len(self._steps)

            def resolve(entry):
                kind = entry[0]
                if kind == "const":
                    return entry[1]
                if kind == "slot":
                    return slots[entry[1]]
                return input_value

            ref = None
            # Channel transport matches ring frames to passes by
            # per-actor FIFO order, so one pass's submissions must not
            # interleave with another's (concurrent execute callers).
            # Submissions only enqueue — the lock is held briefly.
            # The lock also covers re-planning (a channel-recovery DAG
            # keeps taking it even while its edges ride the object
            # plane, so an ALIVE event can swing them back to rings).
            # One trace per pass: the driver-side span is the root, and
            # every step submitted under it (local or cross-process)
            # attaches to the same trace id.
            with _tracing.span("dag.execute") as _span, \
                    self._submit_order_lock if (
                    self._channel_edges or self._chan_recovery) \
                    else _NULL_CTX:
                # The driver-side record of this pass: stamped with the
                # pass's root trace id (the span just installed it), so
                # `ray_tpu logs --trace <id>` returns the driver line
                # next to every worker's task records.  Lazy %-args —
                # this sits on the pass hot path (raylint log-hygiene).
                if _log.isEnabledFor(logging.INFO):
                    _log.info("dag pass trace=%s steps=%d",
                              _span.trace_id, len(self._steps))
                self._maybe_replan()
                for step in self._steps:
                    args = tuple(resolve(e) for e in step.arg_plan)
                    kwargs = {k: resolve(e)
                              for k, e in step.kw_plan.items()}
                    ref = step.submit(*args, **kwargs)
                    slots[step.out_slot] = ref
            if self._multi_output is not None:
                out = [resolve(e) for e in self._multi_output]
                tails = [o for o in out
                         if hasattr(o, "_on_completed")]
            else:
                out = ref if ref is not None else input_value
                tails = [ref] if ref is not None else []
            if not tails:
                release_all(())
                return out
            # Backpressure releases when EVERY output of this pass
            # completes; the refs are held meanwhile so a
            # fire-and-forget caller can't free them early (freed
            # objects drop their pending completion callbacks).
            pending = [len(tails)]

            def one_done(_obj=None):
                # A pass dying to a data-plane fault marks the ring
                # plan dirty: the next execute tears down and rebuilds
                # (restart-aware recovery).
                from ..exceptions import (ActorError, ChannelError,
                                          ObjectLostError)

                err = getattr(_obj, "error", None)
                if isinstance(err, (ActorError, ChannelError,
                                    ObjectLostError)):
                    _dag_metrics()["pass_failures"].inc()
                    self._record_pass_failure(err)
                    if self._chan_recovery:
                        self._rings_dirty = True
                with rel_lock:
                    pending[0] -= 1
                    last = pending[0] == 0
                if last:
                    release_all(tails)

            for t in tails:
                self._holding.add(t)
            for t in tails:
                t._on_completed(one_done)
            return out
        except BaseException:
            release_all(())
            raise

    def teardown(self):
        import ray_tpu

        if self._state_listener is not None:
            from ..core.runtime import try_get_runtime

            rt = try_get_runtime()
            if rt is not None and rt.cluster is not None:
                rt.cluster.remove_actor_state_listener(
                    self._state_listener)
            self._state_listener = None
        self._chan_recovery = False
        for node, handle in self._actors:
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
            # Clear the node's cached handle: a recompile (or the
            # interpreted path) must not route to the killed actor.
            with node._handle_lock:
                if node._handle is handle:
                    node._handle = None
        self._actors = []
        if self._channel_edges:
            from ..experimental.channel import destroy_channel_at

            # After the kills so no new frames are produced; destroying
            # wakes any task still blocked on a ring (ChannelClosed).
            # Rings hosted by other node processes are destroyed there
            # (channel_destroy RPC) so their files and cached endpoint
            # mappings don't outlive the DAG.
            for path in self._channel_edges.values():
                destroy_channel_at(path,
                                   self._channel_nodes.get(path, ()))
            self._channel_edges = {}
            self._channel_nodes = {}
