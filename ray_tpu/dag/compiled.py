"""Compiled DAG execution (aDAG equivalent).

Reference semantics: python/ray/dag/compiled_dag_node.py:691 — a bound
DAG compiles once into a static schedule over pre-resolved endpoints
with pre-allocated channels, replacing per-call graph interpretation.

What compiling buys here (TPU-first reading of the same idea):
- The graph is FLATTENED ONCE into a slot-indexed step plan: per
  execute there is no DAG traversal, no per-node dict building, no
  re-binding — each step is (endpoint, arg-slot template).
- DAG actors are created eagerly at compile time with their endpoints
  pre-resolved into the plan (the reference's per-actor execution
  loops); constructor args must be static.
- Executions PIPELINE: ``execute`` returns refs immediately and up to
  ``max_in_flight`` executions overlap (submission backpressure via
  completion callbacks) — the aDAG property that lets a pipeline
  schedule keep every stage busy.
- The channel role is played by the object plane: in-process consumers
  share sealed values zero-copy; cross-node consumers pull primary
  copies over the chunk protocol.  (jax arrays additionally move
  device-to-device only at true process boundaries.)
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from .dag_node import (ClassMethodNode, ClassNode, DAGNode, FunctionNode,
                       InputNode, MultiOutputNode)


class _Step:
    __slots__ = ("submit", "arg_plan", "kw_plan", "out_slot")

    def __init__(self, submit, arg_plan, kw_plan, out_slot):
        self.submit = submit      # fn(*args, **kwargs) -> ref
        self.arg_plan = arg_plan  # [("const", v) | ("slot", i) | ("input",)]
        self.kw_plan = kw_plan    # {k: same}
        self.out_slot = out_slot


class CompiledDAG:
    def __init__(self, root: DAGNode, max_in_flight: int = 8,
                 **_options):
        self._root = root
        self._in_flight = threading.Semaphore(max(1, max_in_flight))
        self._slots_of: Dict[int, int] = {}
        self._steps: List[_Step] = []
        self._multi_output: Optional[List[int]] = None
        # (class_node, handle): teardown kills AND clears the node's
        # cached handle so a recompile makes a fresh actor.
        self._actors: List[Tuple[Any, Any]] = []
        # Refs of in-flight executions: held until completion so a
        # fire-and-forget caller can't free the tail object before its
        # callback fires (a freed object drops pending callbacks and
        # would leak the semaphore slot).
        self._holding: set = set()
        try:
            self._compile(root)
        except BaseException:
            # A failed compile must not leak the actors it already
            # created (there is no CompiledDAG object to teardown).
            self.teardown()
            raise

    # ------------------------------------------------------------ compile
    def _compile(self, root: DAGNode):
        order: List[DAGNode] = []
        seen: Dict[int, bool] = {}

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen[id(node)] = True
            for child in node._children():
                visit(child)
            order.append(node)

        visit(root)

        for node in order:
            if isinstance(node, InputNode):
                continue
            if isinstance(node, ClassNode):
                self._ensure_actor(node)
                continue
            if isinstance(node, MultiOutputNode):
                self._multi_output = [
                    self._plan_entry(o) for o in node._bound_args]
                continue
            arg_plan = [self._plan_entry(a) for a in node._bound_args]
            kw_plan = {k: self._plan_entry(v)
                       for k, v in node._bound_kwargs.items()}
            out_slot = len(self._slots_of)
            self._slots_of[id(node)] = out_slot
            self._steps.append(_Step(
                self._make_submit(node), arg_plan, kw_plan, out_slot))

    def _plan_entry(self, v) -> Tuple:
        if isinstance(v, InputNode):
            return ("input",)
        if isinstance(v, ClassNode):
            # An actor handle passed as a task argument resolves to the
            # compile-time actor (same as the interpreted path).
            return ("const", self._ensure_actor(v))
        if isinstance(v, DAGNode):
            slot = self._slots_of.get(id(v))
            if slot is None:
                raise ValueError(
                    "DAG argument is not in topological order "
                    "(unsupported node kind in compiled mode?)")
            return ("slot", slot)
        return ("const", v)

    def _static_args(self, node: DAGNode):
        for a in list(node._bound_args) + \
                list(node._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                raise ValueError(
                    "compiled DAG actors must have static constructor "
                    "args (reference aDAG constraint)")
        return node._bound_args, node._bound_kwargs

    def _ensure_actor(self, node: ClassNode):
        """Long-lived DAG actor, created once at compile
        (compiled_dag_node.py: actors own their loops).  Constructor
        args must be static.  ClassNodes are only reachable through
        their method nodes' _target (not _children), so creation is
        on demand, under the node's own handle lock (the interpreted
        path shares it)."""
        with node._handle_lock:
            if node._handle is None:
                args, kwargs = self._static_args(node)
                cls = (node._actor_class.options(**node._options)
                       if node._options else node._actor_class)
                node._handle = cls.remote(*args, **kwargs)
                self._actors.append((node, node._handle))
            return node._handle

    def _make_submit(self, node: DAGNode):
        if isinstance(node, FunctionNode):
            handle = (node._remote_fn.options(**node._options)
                      if node._options else node._remote_fn)
            return handle.remote
        if isinstance(node, ClassMethodNode):
            target = node._target
            if isinstance(target, ClassNode):
                actor = self._ensure_actor(target)
            else:
                actor = target
            return getattr(actor, node._method_name).remote
        raise TypeError(f"cannot compile node {type(node).__name__}")

    # ------------------------------------------------------------ execute
    def execute(self, *input_values) -> Any:
        """Run one pass over the static plan; returns the terminal
        ref(s) immediately.  Up to ``max_in_flight`` passes overlap."""
        input_value = input_values[0] if input_values else None
        self._in_flight.acquire()
        released = [False]
        rel_lock = threading.Lock()

        def release_all(refs):
            with rel_lock:
                if released[0]:
                    return
                released[0] = True
            for r in refs:
                self._holding.discard(r)
            self._in_flight.release()

        try:
            slots: List[Any] = [None] * len(self._steps)

            def resolve(entry):
                kind = entry[0]
                if kind == "const":
                    return entry[1]
                if kind == "slot":
                    return slots[entry[1]]
                return input_value

            ref = None
            for step in self._steps:
                args = tuple(resolve(e) for e in step.arg_plan)
                kwargs = {k: resolve(e)
                          for k, e in step.kw_plan.items()}
                ref = step.submit(*args, **kwargs)
                slots[step.out_slot] = ref
            if self._multi_output is not None:
                out = [resolve(e) for e in self._multi_output]
                tails = [o for o in out
                         if hasattr(o, "_on_completed")]
            else:
                out = ref if ref is not None else input_value
                tails = [ref] if ref is not None else []
            if not tails:
                release_all(())
                return out
            # Backpressure releases when EVERY output of this pass
            # completes; the refs are held meanwhile so a
            # fire-and-forget caller can't free them early (freed
            # objects drop their pending completion callbacks).
            pending = [len(tails)]

            def one_done(_obj=None):
                with rel_lock:
                    pending[0] -= 1
                    last = pending[0] == 0
                if last:
                    release_all(tails)

            for t in tails:
                self._holding.add(t)
            for t in tails:
                t._on_completed(one_done)
            return out
        except BaseException:
            release_all(())
            raise

    def teardown(self):
        import ray_tpu

        for node, handle in self._actors:
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
            # Clear the node's cached handle: a recompile (or the
            # interpreted path) must not route to the killed actor.
            with node._handle_lock:
                if node._handle is handle:
                    node._handle = None
        self._actors = []
