"""Compiled DAG execution (aDAG equivalent).

Reference semantics: python/ray/dag/compiled_dag_node.py:691 — a bound
DAG is compiled once into per-actor static execution loops connected by
pre-allocated channels, replacing per-call RPC with channel write/read.

Current implementation: caches the topological submission plan so
``execute`` re-walks a precomputed order (no re-traversal / re-binding);
channel-based execution over mutable objects + ICI p2p lands with the
cluster runtime (ray_tpu.core.node).
"""

from __future__ import annotations

from typing import Any, Dict, List

from .dag_node import DAGNode, InputNode


class CompiledDAG:
    def __init__(self, root: DAGNode, **_options):
        self._root = root
        self._order = self._toposort(root)

    @staticmethod
    def _toposort(root: DAGNode) -> List[DAGNode]:
        seen: Dict[int, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for child in node._children():
                visit(child)
            order.append(node)

        visit(root)
        return order

    def execute(self, *input_values) -> Any:
        input_value = input_values[0] if input_values else None
        cache: Dict[int, Any] = {}
        for node in self._order:
            if not isinstance(node, InputNode):
                node._execute_impl(cache, input_value)
        return self._root._execute_impl(cache, input_value)

    def teardown(self):
        pass
