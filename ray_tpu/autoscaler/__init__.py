"""Autoscaler: demand-driven node provisioning.

Reference: python/ray/autoscaler — the v1 monitor loop
(_private/autoscaler.py + monitor.py) sizes the cluster from pending
resource demands via resource_demand_scheduler.py; v2 restates it as a
declarative reconciler (v2/instance_manager/reconciler.py) over cloud
``NodeProvider``s; fake_multi_node provides a local provider for tests.

Shape here: the head keeps a ledger of infeasible placements
(pending_demand RPC); the ``Autoscaler`` reconciler polls it, bin-packs
the unmet demands against the configured node type, launches nodes
through a ``NodeProvider``, and terminates idle nodes past
``idle_timeout_s`` down to ``min_nodes``.  ``LocalNodeProvider``
launches real worker subprocesses (the fake_multi_node analogue —
and exactly how a single-host TPU pod slice is carved up); cloud
providers implement the same 3-method interface.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal provider contract (reference: autoscaler NodeProvider):
    create / terminate / list."""

    def create_node(self, resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_tag: str) -> None:
        raise NotImplementedError

    def live_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Worker subprocesses on this host (reference:
    autoscaler/_private/fake_multi_node)."""

    def __init__(self, head_address: str,
                 env: Optional[Dict[str, str]] = None):
        self.head_address = head_address
        self._env = env
        self._procs: Dict[str, Any] = {}
        self._n = 0

    def create_node(self, resources: Dict[str, float]) -> str:
        from ..core.node import start_worker_process

        res = dict(resources)
        cpus = res.pop("CPU", 1.0)
        tag = f"auto-{self._n}"
        self._n += 1
        self._procs[tag] = start_worker_process(
            self.head_address, num_cpus=cpus, resources=res or None,
            node_name=tag, env=self._env)
        return tag

    def terminate_node(self, node_tag: str) -> None:
        proc = self._procs.pop(node_tag, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()

    def live_nodes(self) -> List[str]:
        return [t for t, p in self._procs.items() if p.poll() is None]

    def shutdown(self):
        for tag in list(self._procs):
            self.terminate_node(tag)


class Autoscaler:
    """Reconciler loop (reference v2/instance_manager/reconciler.py):
    observe demand → compute target → converge the provider."""

    def __init__(self, head_address: str, provider: NodeProvider, *,
                 node_resources: Optional[Dict[str, float]] = None,
                 min_nodes: int = 0, max_nodes: int = 4,
                 idle_timeout_s: float = 60.0,
                 poll_interval_s: float = 1.0,
                 boot_timeout_s: float = 120.0):
        from ..cluster.rpc import ReconnectingClient

        self.provider = provider
        self.node_resources = dict(node_resources or {"CPU": 1.0})
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self.poll_interval_s = poll_interval_s
        self._head = ReconnectingClient(head_address)
        self._stop = threading.Event()
        self._idle_since: Dict[str, float] = {}
        # Launched-but-not-yet-registered nodes: tag -> launch time.
        # Without this, an infeasible placement launches a node per poll
        # tick until the demand ledger ages out (reference: v1
        # autoscaler's pending-launch accounting in
        # resource_demand_scheduler.py).
        self._pending_launches: Dict[str, float] = {}
        # Size to the provider's boot-to-register time (cloud TPU VMs
        # take minutes); too short resurfaces the duplicate-launch storm.
        self._boot_timeout_s = boot_timeout_s
        self.num_launched = 0
        self.num_terminated = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ loop
    def _loop(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self._reconcile()
            except Exception:
                pass

    def _reconcile(self):
        demands = self._head.call("pending_demand",
                                  {"window_s": 10.0}, timeout=5.0)
        nodes = self._head.call("list_nodes", {}, timeout=5.0)
        live = self.provider.live_nodes()
        # A launch is pending until the node registers with the head (by
        # name) or the boot timeout lapses; pending launches count toward
        # the target so repeated polls don't relaunch for the same demand.
        registered = {n.get("name") or "" for n in nodes}
        now = time.monotonic()
        self._pending_launches = {
            tag: t for tag, t in self._pending_launches.items()
            if tag not in registered and now - t < self._boot_timeout_s
            and tag in live}
        # Scale up: bin-pack unmet demands onto hypothetical nodes of
        # the configured type (reference:
        # resource_demand_scheduler.py get_nodes_to_launch).
        needed = self._nodes_needed(demands)
        want = needed - len(self._pending_launches)
        can_add = min(want, self.max_nodes - len(live))
        for _ in range(max(0, can_add)):
            tag = self.provider.create_node(self.node_resources)
            self._pending_launches[tag] = time.monotonic()
            self.num_launched += 1
        if needed > 0:
            # Unmet demand (even if fully covered by pending launches):
            # never scale down while nodes are booting to serve it.
            return
        # Scale down: terminate nodes idle past the timeout, keeping
        # min_nodes (reference: NodeIdleTerminationPolicy).
        busy_names = set()
        for n in nodes:
            used = {
                k: n["total"].get(k, 0) - n["available"].get(k, 0)
                for k in n["total"]}
            if any(v > 1e-9 for k, v in used.items() if k != "memory"):
                busy_names.add(n.get("name") or "")
        now = time.monotonic()
        live = self.provider.live_nodes()
        for tag in live:
            if tag in busy_names or tag in self._pending_launches:
                # Busy, or launched and still booting — a node that has
                # not yet registered must not be reaped as "idle".
                self._idle_since.pop(tag, None)
                continue
            since = self._idle_since.setdefault(tag, now)
            if (now - since >= self.idle_timeout_s
                    and len(self.provider.live_nodes()) > self.min_nodes):
                self.provider.terminate_node(tag)
                self._idle_since.pop(tag, None)
                self.num_terminated += 1

    def _nodes_needed(self, demands: List[Dict[str, float]]) -> int:
        """First-fit-decreasing bin pack of unmet demands into nodes of
        the configured shape; demands that can never fit are skipped."""
        shape = self.node_resources
        feasible = [d for d in demands
                    if all(shape.get(k, 0) >= v for k, v in d.items())]
        if not feasible:
            return 0
        feasible.sort(key=lambda d: -sum(d.values()))
        bins: List[Dict[str, float]] = []
        for d in feasible:
            placed = False
            for b in bins:
                if all(b.get(k, 0) >= v for k, v in d.items()):
                    for k, v in d.items():
                        b[k] = b.get(k, 0) - v
                    placed = True
                    break
            if not placed:
                b = dict(shape)
                for k, v in d.items():
                    b[k] = b.get(k, 0) - v
                bins.append(b)
        return len(bins)

    def shutdown(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self._head.close()
