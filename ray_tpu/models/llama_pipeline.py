"""Per-stage Llama functions for CROSS-PROCESS pipeline parallelism.

The in-jit GPipe schedule (parallel/pipeline.py) runs all stages in one
XLA program on one mesh — the right shape *within* an ICI domain.  A
multi-slice pod needs the other half: each slice runs its own jitted
stage program and activations cross DCN between processes
(train/cross_pipeline.py).  This module supplies the stage-local math:

- ``stage_slice(params, stage, n)`` — the stage's parameter subtree
  (embedding on stage 0, L/n layer block each, norm+head on the last).
- ``make_stage_fwd / make_stage_fwd_loss`` — jittable stage programs.
- Backward is activation recomputation at stage granularity: the stage
  re-runs its forward under ``jax.vjp`` at backward time, so only the
  stage *input* is kept per in-flight microbatch (GPipe memory M×input,
  not M×activations).

Reference: Ray ships no pipeline-training schedule; its intended
substrate is compiled-graph channels + overlap schedules
(python/ray/dag/dag_node_operation.py:506-539).  SURVEY §5.8: DCN =
cross-slice pipelines over channels.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from .llama import (LlamaConfig, decoder_layer, _get_attention_fn,
                    matmul, rms_norm, rope_table)

PyTree = Any


def check_pipeline_config(config: LlamaConfig, n_stages: int):
    if n_stages < 2:
        raise ValueError("cross-process pipeline needs >= 2 stages")
    if config.n_layers % n_stages:
        raise ValueError(
            f"{config.n_layers} layers not divisible by {n_stages} stages")
    if config.tie_embeddings:
        raise ValueError(
            "tie_embeddings shares one parameter between stage 0 "
            "(embedding) and the last stage (head); untie for "
            "cross-process pipeline")
    if config.moe_experts > 0:
        raise NotImplementedError(
            "MoE layers in cross-process pipeline stages: route the "
            "aux loss through the activation protocol first")
    if config.attention_impl == "ring":
        raise NotImplementedError(
            "ring attention needs a seq mesh axis inside the stage "
            "program; compose it via the stage mesh_spec instead")


def stage_slice(params: PyTree, stage: int, n_stages: int) -> PyTree:
    """The parameter subtree stage ``stage`` owns.

    Slicing a fully-initialized tree keeps init numerics identical to
    the single-process model (parity tests depend on it).  At 8B+ scale
    initialize per-stage instead: build the full tree under
    ``jax.eval_shape`` and materialize only this slice.
    """
    L = jax.tree.leaves(params["layers"])[0].shape[0]
    per = L // n_stages
    lo, hi = stage * per, (stage + 1) * per
    out: Dict[str, Any] = {
        "layers": jax.tree.map(lambda a: a[lo:hi], params["layers"])}
    if stage == 0:
        out["embed_tokens"] = params["embed_tokens"]
    if stage == n_stages - 1:
        out["final_norm"] = params["final_norm"]
        out["lm_head"] = params["lm_head"]
    return out


def _run_layers(x: jax.Array, layers: PyTree, config: LlamaConfig):
    """Scan the stage's stacked layers over ``x`` (B, S, E)."""
    c = config
    B, S = x.shape[0], x.shape[1]
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    sin, cos = rope_table(positions, c.head_dim, c.rope_theta)
    block = functools.partial(
        decoder_layer, sin=sin, cos=cos, positions=positions, config=c,
        attention_fn=_get_attention_fn(c))
    if c.remat:
        from .llama import _remat_policy

        block = jax.checkpoint(block, policy=_remat_policy(c))

    def body(h, layer):
        return block(h, layer), None

    x, _ = jax.lax.scan(body, x, layers)
    return x


def make_stage_fwd(config: LlamaConfig, first: bool) -> Callable:
    """``fwd(stage_params, inp) -> h_out``; inp is tokens (B, S) int32
    on stage 0, hidden states (B, S, E) downstream."""

    def fwd(sl: PyTree, inp: jax.Array) -> jax.Array:
        x = (sl["embed_tokens"].astype(config.dtype)[inp]
             if first else inp.astype(config.dtype))
        return _run_layers(x, sl["layers"], config)

    return fwd


def make_stage_fwd_loss(config: LlamaConfig) -> Callable:
    """Last stage: ``fwd_loss(stage_params, h_in, tokens) -> loss``.

    Mirrors llama.loss_fn's full-length-forward-then-slice convention
    (llama.py loss_fn) so pipeline loss == single-process loss.
    """
    c = config

    def fwd_loss(sl: PyTree, h_in: jax.Array,
                 tokens: jax.Array) -> jax.Array:
        x = _run_layers(h_in.astype(c.dtype), sl["layers"], c)
        x = rms_norm(x, sl["final_norm"], c.norm_eps)
        logits = matmul(x, sl["lm_head"].astype(c.dtype))[:, :-1]
        targets = tokens[:, 1:]
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None],
                                   axis=-1).squeeze(-1)
        return jnp.mean(logz - gold)

    return fwd_loss
