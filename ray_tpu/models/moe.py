"""Mixture-of-Experts FFN with expert parallelism.

Reference: Ray implements NO MoE/EP (SURVEY §2.3 — it only offers
placement-group primitives); the TPU build must supply the strategy
natively.  Design is GShard/Switch-style DENSE dispatch, the
TPU-idiomatic formulation: top-k routing builds a (tokens, experts,
capacity) one-hot dispatch tensor, so dispatch/combine are einsums
that run on the MXU with static shapes — no ragged buffers, no
data-dependent shapes.  Sharding the expert dimension over the
``expert`` mesh axis (logical axis "expert") makes XLA lower the
dispatch/combine einsums to all_to_all over ICI automatically.

Tokens beyond an expert's capacity are dropped (their combine weight
is zero and the residual path carries them) — standard Switch
semantics; ``capacity_factor`` trades drop rate for padding compute.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import with_logical_constraint

PyTree = Any


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    intermediate_size: int
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16


def init_moe_params(rng: jax.Array, config: MoEConfig,
                    dtype=jnp.float32) -> PyTree:
    from ray_tpu.models.llama import init_dense

    c = config
    k_router, k_gate, k_up, k_down = jax.random.split(rng, 4)

    def dense(key, shape, fan_in):
        return init_dense(key, shape, fan_in, dtype)

    E, D, H = c.n_experts, c.hidden_size, c.intermediate_size
    return {
        "router": dense(k_router, (D, E), D),
        "w_gate": dense(k_gate, (E, D, H), D),
        "w_up": dense(k_up, (E, D, H), D),
        "w_down": dense(k_down, (E, H, D), H),
    }


def moe_param_logical_axes() -> Dict[str, Tuple]:
    return {
        "router": (None, "expert"),
        "w_gate": ("expert", "embed", "mlp"),
        "w_up": ("expert", "embed", "mlp"),
        "w_down": ("expert", "mlp", "embed"),
    }


def _einsum(eq, *args):
    """bf16×bf16 einsum with f32 MXU accumulation (same measured
    rationale as llama.matmul: operand-dtype accumulation drops XLA
    onto a ~4-5x slower path)."""
    out = jnp.einsum(eq, *args, preferred_element_type=jnp.float32)
    return out.astype(args[0].dtype)


def _route(xt: jax.Array, router: jax.Array, k: int):
    """Shared by moe_ffn and the parity reference so the two can't
    drift: f32 softmax routing + renormalized top-k gates."""
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        router.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)          # (T, E)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # (T, K)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)
    return probs, gate_vals, expert_idx


def moe_ffn(x: jax.Array, params: PyTree, config: MoEConfig
            ) -> Tuple[jax.Array, jax.Array]:
    """x: (B, S, D) → (out (B, S, D), aux_loss scalar).

    aux_loss is the Switch load-balancing loss (mean fraction of
    tokens per expert × mean router prob per expert × E); add it to
    the training loss scaled by ~1e-2."""
    c = config
    B, S, D = x.shape
    T = B * S
    E, K = c.n_experts, c.top_k
    dt = c.dtype
    xt = x.reshape(T, D).astype(dt)

    probs, gate_vals, expert_idx = _route(xt, params["router"], K)

    capacity = int(max(1, round(T * K / E * c.capacity_factor)))

    # Position of each (token, k) within its expert's capacity buffer:
    # cumulative count of prior assignments to the same expert.
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (T,K,E)
    flat = onehot.reshape(T * K, E)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat).reshape(T, K, E)
    pos = jnp.sum(pos_in_expert * onehot, axis=-1)            # (T, K)
    keep = pos < capacity

    # Dense dispatch tensor (T, E, C): 1 where token t goes to slot
    # (e, c).  combine = dispatch weighted by the gate.
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    t_idx = jnp.arange(T)[:, None].repeat(K, 1)
    dispatch = dispatch.at[
        t_idx.reshape(-1),
        expert_idx.reshape(-1),
        jnp.clip(pos, 0, capacity - 1).reshape(-1),
    ].add(keep.astype(jnp.float32).reshape(-1))
    gate_te = jnp.zeros((T, E), jnp.float32).at[
        t_idx.reshape(-1), expert_idx.reshape(-1)
    ].add((gate_vals * keep).reshape(-1))
    combine = dispatch * gate_te[:, :, None]

    # Expert inputs (E, C, D): the einsum's sharding constraint on the
    # expert dim is what turns this into an all_to_all over ICI.
    expert_in = _einsum("tec,td->ecd", dispatch.astype(dt), xt)
    expert_in = with_logical_constraint(expert_in, "expert", None, None)

    h = _einsum("ecd,edh->ech", expert_in, params["w_gate"].astype(dt))
    u = _einsum("ecd,edh->ech", expert_in, params["w_up"].astype(dt))
    act = jax.nn.silu(h) * u
    expert_out = _einsum("ech,ehd->ecd", act,
                         params["w_down"].astype(dt))
    expert_out = with_logical_constraint(expert_out,
                                         "expert", None, None)

    out = _einsum("tec,ecd->td", combine.astype(dt), expert_out)

    # Switch aux loss: E * mean_e(frac_tokens_e * mean_prob_e).
    top1 = jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32)
    frac_tokens = top1.mean(0)
    mean_prob = probs.mean(0)
    aux = E * jnp.sum(frac_tokens * mean_prob)
    return out.reshape(B, S, D).astype(x.dtype), aux


def moe_ffn_reference(x: jax.Array, params: PyTree, config: MoEConfig
                      ) -> jax.Array:
    """Slow per-token loop-free reference (no capacity drops) for
    parity tests at small shapes: every token visits its top-k experts
    exactly."""
    c = config
    B, S, D = x.shape
    dt = c.dtype
    xt = x.reshape(-1, D).astype(dt)
    _probs, gate_vals, expert_idx = _route(xt, params["router"],
                                           c.top_k)

    def per_expert(e):
        h = xt.astype(dt) @ params["w_gate"][e].astype(dt)
        u = xt.astype(dt) @ params["w_up"][e].astype(dt)
        return (jax.nn.silu(h) * u) @ params["w_down"][e].astype(dt)

    all_out = jnp.stack([per_expert(e)
                         for e in range(c.n_experts)])  # (E, T, D)
    out = jnp.zeros_like(xt, dtype=jnp.float32)
    for k in range(c.top_k):
        picked = all_out[expert_idx[:, k], jnp.arange(xt.shape[0])]
        out = out + gate_vals[:, k:k + 1] * picked.astype(jnp.float32)
    return out.reshape(B, S, D).astype(x.dtype)
