"""Llama-family decoder LM, TPU-native.

Design (idiomatic jax/XLA, not a torch translation):

- **Functional**: params are a plain pytree; ``forward(params, tokens)``
  is pure and jit/pjit-friendly.
- **Scan over layers**: per-layer weights are stacked on a leading
  ``layers`` dim and the block runs under ``jax.lax.scan`` — one trace,
  O(1) compile time in depth, and the ``layers`` dim is the natural
  pipeline-parallel shard axis.
- **Logical shardings**: every weight/activation dim carries a logical
  axis name resolved by :mod:`ray_tpu.parallel.sharding`; the same model
  runs DP/FSDP/TP/SP by swapping rule tables.
- **bf16 compute, f32 params/optimizer**: matmuls hit the MXU in
  bfloat16; the master copy and adam moments stay float32.
- **Pluggable attention**: ``config.attention_impl`` selects plain
  einsum attention, the Pallas flash kernel, or ring attention
  (sequence-parallel) — all causal, all identical numerics up to
  blocking.

Parity note: the reference trains models only through wrappers around
torch (train/torch/train_loop_utils.py:162); there is no reference
model to port, so shapes follow the public Llama-2/3 architecture.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import with_logical_constraint

PyTree = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    intermediate_size: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # "dot" (einsum), "flash" (Pallas kernel), "ring" (sequence-parallel
    # ring attention over the "seq" mesh axis).
    attention_impl: str = "dot"
    remat: bool = True
    # Rematerialization policy for the per-layer checkpoint wrapper:
    # "full" recomputes everything in backward (min memory, ~2N extra
    # flops/token); "dots" saves matmul/einsum outputs with no batch
    # dims (XLA's dots_with_no_batch_dims_saveable — but it saves the
    # F32 dot results, ~830 MB/layer at bench shapes: OOM on one v5e);
    # "attn" saves only the flash kernel's residuals (q/k/v/o bf16 +
    # width-1 lse, ~129 MB/layer) so backward skips re-running the
    # attention forward while still rematerializing the FFN — the best
    # measured time/memory point on v5e; ignored when remat=False.
    remat_policy: str = "full"
    # Tie input embedding and LM head (small models).
    tie_embeddings: bool = False
    # lax.scan unroll factor for the layer stack: >1 lets XLA fuse
    # across adjacent layers (fewer loop-carried DUS/sequencing
    # overheads) at the cost of compile time.
    scan_unroll: int = 1
    # Flash-attention tile sizes (None = kernel default, currently
    # 1024).  Exposed as a config knob so the MFU sweep
    # (profile_mfu.py --attn-block) can tune them per chip/shape and
    # the winner can be recorded on the preset.
    attn_block_q: Optional[int] = None
    attn_block_k: Optional[int] = None
    # >0 enables REAL pipeline parallelism when the active mesh has a
    # pipe axis of size >1: the layer stack runs as a GPipe microbatch
    # schedule over pipe stages (parallel/pipeline.py) instead of one
    # scan.  Value = number of microbatches.
    pipeline_microbatches: int = 0
    # >0 replaces every layer's dense FFN with a GShard/Switch MoE FFN
    # (models/moe.py) of this many experts, sharded over the "expert"
    # mesh axis.  The Switch aux loss is added to the training loss
    # scaled by moe_aux_weight.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    @classmethod
    def debug(cls, **kw) -> "LlamaConfig":
        """Tiny config for tests/CI (runs on CPU in <1s)."""
        base = dict(vocab_size=256, hidden_size=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, head_dim=16, intermediate_size=128,
                    max_seq_len=128, rope_theta=10000.0, remat=False,
                    tie_embeddings=True)
        base.update(kw)
        return cls(**base)

    @classmethod
    def moe_debug(cls, **kw) -> "LlamaConfig":
        """Tiny MoE config (expert-parallel dryruns/tests on CPU)."""
        base = dict(moe_experts=4, moe_top_k=2)
        base.update(kw)
        return cls.debug(**base)

    @classmethod
    def llama_moe_1b(cls, **kw) -> "LlamaConfig":
        """Switch-style MoE bench model: 8 experts over the 440M dense
        trunk (~1.6B total params, ~440M active/token)."""
        base = dict(vocab_size=32000, hidden_size=1024, n_layers=24,
                    n_heads=8, n_kv_heads=8, head_dim=128,
                    intermediate_size=4096, max_seq_len=2048,
                    rope_theta=10000.0, tie_embeddings=True,
                    attention_impl="flash", moe_experts=8, moe_top_k=2)
        base.update(kw)
        return cls(**base)

    @classmethod
    def llama_125m(cls, **kw) -> "LlamaConfig":
        base = dict(vocab_size=32000, hidden_size=768, n_layers=12,
                    n_heads=6, n_kv_heads=6, head_dim=128,
                    intermediate_size=2048, max_seq_len=2048,
                    rope_theta=10000.0, tie_embeddings=True)
        base.update(kw)
        return cls(**base)

    @classmethod
    def llama_440m(cls, **kw) -> "LlamaConfig":
        """Single-chip bench model: largest config that trains with
        f32 adam state in 16 GB HBM (measured on v5e).

        head_dim is 128, NOT the GPU-lineage 64: every (…, head_dim)
        tensor tiles the TPU's (8,128) layout exactly (64 pads 2x in
        HBM) and QK^T runs the MXU at full systolic depth.  Measured
        v5e, identical param count: 32.7k tok/s @ 43.4% MFU vs 24.6k @
        32.6% with 16 heads x 64.  remat_policy='attn' saves the flash
        kernel's residuals so backward never re-runs attention."""
        base = dict(vocab_size=32000, hidden_size=1024, n_layers=24,
                    n_heads=8, n_kv_heads=8, head_dim=128,
                    intermediate_size=4096, max_seq_len=2048,
                    rope_theta=10000.0, tie_embeddings=True,
                    attention_impl="flash", remat_policy="attn")
        base.update(kw)
        return cls(**base)

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        base = dict(vocab_size=32000, hidden_size=4096, n_layers=32,
                    n_heads=32, n_kv_heads=32, head_dim=128,
                    intermediate_size=11008, max_seq_len=4096,
                    rope_theta=10000.0)
        base.update(kw)
        return cls(**base)

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        base = dict(vocab_size=128256, hidden_size=4096, n_layers=32,
                    n_heads=32, n_kv_heads=8, head_dim=128,
                    intermediate_size=14336, max_seq_len=8192,
                    rope_theta=500000.0)
        base.update(kw)
        return cls(**base)


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def param_logical_axes(config: LlamaConfig) -> Dict[str, Any]:
    """Pytree (matching init_params) of per-dim logical axis names."""
    if config.moe_experts > 0:
        ffn_axes = {
            "router": ("layers", None, "expert"),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        }
    else:
        ffn_axes = {
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        }
    axes = {
        "embed_tokens": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", None),
            **ffn_axes,
        },
        "final_norm": (None,),
    }
    if not config.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_dense(key, shape, fan_in, dtype=jnp.float32):
    """Truncated-normal fan-in-scaled initializer shared across model
    families (llama, moe)."""
    scale = fan_in ** -0.5
    return (jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                        jnp.float32) * scale).astype(dtype)


def init_params(rng: jax.Array, config: LlamaConfig,
                dtype: Any = jnp.float32) -> PyTree:
    """Initialize the stacked-layer param pytree (truncated-normal,
    fan-in scaled; norms at 1)."""
    c = config
    keys = jax.random.split(rng, 8)

    def dense(key, shape, fan_in):
        return init_dense(key, shape, fan_in, dtype)

    L = c.n_layers
    if c.moe_experts > 0:
        E = c.moe_experts
        ffn = {
            "router": dense(keys[5], (L, c.hidden_size, E), c.hidden_size),
            "w_gate": dense(keys[6],
                            (L, E, c.hidden_size, c.intermediate_size),
                            c.hidden_size),
            "w_up": dense(jax.random.fold_in(keys[6], 1),
                          (L, E, c.hidden_size, c.intermediate_size),
                          c.hidden_size),
            "w_down": dense(keys[7],
                            (L, E, c.intermediate_size, c.hidden_size),
                            c.intermediate_size),
        }
    else:
        ffn = {
            "w_gate": dense(keys[5], (L, c.hidden_size, c.intermediate_size),
                            c.hidden_size),
            "w_up": dense(keys[6], (L, c.hidden_size, c.intermediate_size),
                          c.hidden_size),
            "w_down": dense(keys[7], (L, c.intermediate_size, c.hidden_size),
                            c.intermediate_size),
        }
    params = {
        "embed_tokens": dense(keys[0], (c.vocab_size, c.hidden_size),
                              c.hidden_size),
        "layers": {
            "attn_norm": jnp.ones((L, c.hidden_size), dtype),
            "wq": dense(keys[1], (L, c.hidden_size, c.q_dim), c.hidden_size),
            "wk": dense(keys[2], (L, c.hidden_size, c.kv_dim), c.hidden_size),
            "wv": dense(keys[3], (L, c.hidden_size, c.kv_dim), c.hidden_size),
            "wo": dense(keys[4], (L, c.q_dim, c.hidden_size), c.q_dim),
            "mlp_norm": jnp.ones((L, c.hidden_size), dtype),
            **ffn,
        },
        "final_norm": jnp.ones((c.hidden_size,), dtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense(
            jax.random.fold_in(rng, 99), (c.hidden_size, c.vocab_size),
            c.hidden_size)
    return params


def param_count(params: PyTree) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

REMAT_POLICIES = ("full", "dots", "dots_saveable", "attn", "attn_ffn")


def _remat_policy(config: LlamaConfig):
    """Checkpoint policy for the per-layer remat wrapper (see
    LlamaConfig.remat_policy).  "attn_ffn" additionally saves the
    FFN activation ``silu(gate)*up`` next to the flash residuals —
    backward skips recomputing the two up-projection matmuls at
    +intermediate_size bf16/token of residual memory (the next sweep
    point past "attn" when HBM headroom allows; profile_mfu.py
    --remat-policy compares them)."""
    if config.remat_policy not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat_policy {config.remat_policy!r} "
            f"(choose from {REMAT_POLICIES})")
    if config.remat_policy in ("attn", "attn_ffn"):
        from ray_tpu.ops.flash_attention import FLASH_RESIDUAL_NAMES

        names = FLASH_RESIDUAL_NAMES
        if config.remat_policy == "attn_ffn":
            names = names + ("ffn_act",)
        return jax.checkpoint_policies.save_only_these_names(*names)
    return {
        "full": jax.checkpoint_policies.nothing_saveable,
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
    }[config.remat_policy]


def matmul(x: jax.Array, w: jax.Array, out_dtype: Any = None) -> jax.Array:
    """bf16×bf16 matmul with float32 MXU accumulation.

    Measured on v5e: letting the accumulation type default to the
    operand dtype (bf16) drops XLA onto a ~4-5x slower path (26-42
    TF/s vs 139 TF/s with preferred_element_type=f32).  Always
    accumulate f32 and downcast explicitly.
    """
    out = jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return out.astype(out_dtype or x.dtype)


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * scale.astype(jnp.float32)).astype(dtype)


def rope_table(positions: jax.Array, head_dim: int,
               theta: float) -> Tuple[jax.Array, jax.Array]:
    """(sin, cos) tables, shape (..., seq, head_dim/2), float32."""
    freqs = theta ** (-jnp.arange(0, head_dim // 2, dtype=jnp.float32)
                      / (head_dim // 2))
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """x: (batch, seq, heads, head_dim); rotate-half convention.

    Computed in x's dtype (bf16 in training): the f32 round-trip costs
    ~85 ms/step on the 440M bench (measured, v5e) for ~2^-8 relative
    angle precision nobody needs at 2k context; tables stay f32 and are
    cast at the multiply.
    """
    x1, x2 = jnp.split(x, 2, axis=-1)
    sin = sin[:, :, None, :].astype(x.dtype)
    cos = cos[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                           axis=-1)


def dot_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  positions: jax.Array) -> jax.Array:
    """Reference einsum attention, causal, GQA via head broadcast.

    q: (B, S, Hq, D); k/v: (B, S, Hkv, D).  All-jnp so XLA fuses; the
    flash/ring impls are drop-in replacements (ray_tpu.ops).
    """
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv
    qg = q.reshape(B, S, Hkv, group, D)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * (D ** -0.5)
    # Causal mask on absolute positions (supports packed/offset pos).
    mask = positions[:, None, None, :, None] >= positions[:, None, None,
                                                          None, :]
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v,
                     preferred_element_type=jnp.float32).astype(v.dtype)
    return out.reshape(B, S, Hq, D)


def _get_attention_fn(config) -> Callable:
    """Resolve a config (or bare impl name) to the attention callable.
    For the flash path the config's ``attn_block_q``/``attn_block_k``
    tile sizes are bound in (the MFU sweep's tuning knob)."""
    impl = config if isinstance(config, str) else config.attention_impl
    if impl == "dot":
        return dot_attention
    try:
        if impl == "flash":
            from ray_tpu.ops.flash_attention import flash_attention_causal
            if isinstance(config, str):
                return flash_attention_causal
            return functools.partial(
                flash_attention_causal,
                block_q=config.attn_block_q,
                block_k=config.attn_block_k)
        if impl == "ring":
            from ray_tpu.ops.ring_attention import ring_attention_causal
            return ring_attention_causal
    except ImportError as e:
        raise NotImplementedError(
            f"attention_impl={impl!r} requires ray_tpu.ops ({e})") from e
    raise ValueError(f"unknown attention_impl {impl!r}")


def _qkv_rope(x: jax.Array, layer: Dict[str, jax.Array], sin, cos,
              config: LlamaConfig):
    """Shared by the training forward and the KV-cache decode path —
    the conventions here (f32 MXU accumulation via matmul, bf16 rope)
    must stay identical across both."""
    c = config
    B, S, _ = x.shape
    dt = c.dtype
    h = rms_norm(x, layer["attn_norm"], c.norm_eps)
    q = matmul(h, layer["wq"].astype(dt)).reshape(B, S, c.n_heads,
                                                  c.head_dim)
    k = matmul(h, layer["wk"].astype(dt)).reshape(B, S, c.n_kv_heads,
                                                  c.head_dim)
    v = matmul(h, layer["wv"].astype(dt)).reshape(B, S, c.n_kv_heads,
                                                  c.head_dim)
    q = apply_rope(q, sin, cos)
    k = apply_rope(k, sin, cos)
    q = with_logical_constraint(q, "batch", "seq", "heads", "head_dim")
    k = with_logical_constraint(k, "batch", "seq", "kv_heads", "head_dim")
    return q, k, v


def _attn_out_mlp(x: jax.Array, attn: jax.Array,
                  layer: Dict[str, jax.Array],
                  config: LlamaConfig) -> jax.Array:
    """Output projection + MLP half of the block (shared, see
    _qkv_rope).  Constraints are no-ops outside a mesh."""
    c = config
    B, S, _ = x.shape
    dt = c.dtype
    x = x + matmul(attn.reshape(B, S, c.q_dim), layer["wo"].astype(dt))
    x = with_logical_constraint(x, "batch", "seq", None)
    h = rms_norm(x, layer["mlp_norm"], c.norm_eps)
    gate = matmul(h, layer["w_gate"].astype(dt))
    up = matmul(h, layer["w_up"].astype(dt))
    # Named so the "attn_ffn" remat policy can save it (inert under
    # every other policy and outside jax.checkpoint).
    from jax.ad_checkpoint import checkpoint_name

    ff = checkpoint_name(jax.nn.silu(gate) * up, "ffn_act")
    ff = with_logical_constraint(ff, "batch", "seq", "mlp")
    x = x + matmul(ff, layer["w_down"].astype(dt))
    return with_logical_constraint(x, "batch", "seq", None)


def _attn_out_moe(x: jax.Array, attn: jax.Array,
                  layer: Dict[str, jax.Array],
                  config: LlamaConfig) -> Tuple[jax.Array, jax.Array]:
    """MoE twin of _attn_out_mlp: the dense FFN is replaced by the
    expert-parallel Switch FFN; returns (x, layer aux loss)."""
    from ray_tpu.models.moe import MoEConfig, moe_ffn

    c = config
    B, S, _ = x.shape
    dt = c.dtype
    x = x + matmul(attn.reshape(B, S, c.q_dim), layer["wo"].astype(dt))
    x = with_logical_constraint(x, "batch", "seq", None)
    h = rms_norm(x, layer["mlp_norm"], c.norm_eps)
    mcfg = MoEConfig(hidden_size=c.hidden_size,
                     intermediate_size=c.intermediate_size,
                     n_experts=c.moe_experts, top_k=c.moe_top_k,
                     capacity_factor=c.moe_capacity_factor, dtype=dt)
    moe_params = {k: layer[k]
                  for k in ("router", "w_gate", "w_up", "w_down")}
    ff, aux = moe_ffn(h, moe_params, mcfg)
    x = x + ff.astype(x.dtype)
    return with_logical_constraint(x, "batch", "seq", None), aux


def decoder_layer(x: jax.Array, layer: Dict[str, jax.Array],
                  sin: jax.Array, cos: jax.Array, positions: jax.Array,
                  config: LlamaConfig,
                  attention_fn: Callable) -> jax.Array:
    q, k, v = _qkv_rope(x, layer, sin, cos, config)
    attn = attention_fn(q, k, v, positions)
    return _attn_out_mlp(x, attn, layer, config)


def decoder_layer_moe(x: jax.Array, layer: Dict[str, jax.Array],
                      sin: jax.Array, cos: jax.Array,
                      positions: jax.Array, config: LlamaConfig,
                      attention_fn: Callable
                      ) -> Tuple[jax.Array, jax.Array]:
    q, k, v = _qkv_rope(x, layer, sin, cos, config)
    attn = attention_fn(q, k, v, positions)
    return _attn_out_moe(x, attn, layer, config)


# ---------------------------------------------------------------------------
# Forward / loss
# ---------------------------------------------------------------------------

def forward(params: PyTree, tokens: jax.Array, config: LlamaConfig,
            positions: Optional[jax.Array] = None,
            return_aux: bool = False):
    """Logits for next-token prediction.  tokens: (B, S) int32.

    With ``return_aux=True`` returns (logits, aux) where aux is the
    summed MoE load-balancing loss over layers (0.0 for dense)."""
    c = config
    if positions is not None and c.attention_impl != "dot":
        # flash/ring mask on raw row index, not positions — packed or
        # offset sequences would silently attend across boundaries.
        raise NotImplementedError(
            f"custom positions require attention_impl='dot' "
            f"(got {c.attention_impl!r})")
    custom_positions = positions is not None
    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape)
    attention_fn = _get_attention_fn(c)

    # ZeRO-3 semantics for the lookup: all-gather the fsdp-sharded
    # embed dim of the table BEFORE the gather.  Without this the
    # gather's output inherits the table's D-sharding and the SPMD
    # partitioner falls into "involuntary full rematerialization"
    # resharding it to (batch, seq) (observed in the 8-way dryrun).
    emb = with_logical_constraint(
        params["embed_tokens"].astype(c.dtype), "vocab", None)
    x = emb[tokens]
    x = with_logical_constraint(x, "batch", "seq", None)
    sin, cos = rope_table(positions, c.head_dim, c.rope_theta)

    moe = c.moe_experts > 0

    def make_block(sin, cos, positions):
        block = functools.partial(
            decoder_layer_moe if moe else decoder_layer,
            sin=sin, cos=cos, positions=positions, config=c,
            attention_fn=attention_fn)
        if c.remat:
            block = jax.checkpoint(block, policy=_remat_policy(c))
        return block

    from ray_tpu.parallel.sharding import current_mesh

    mesh = current_mesh()
    aux_total = jnp.zeros((), jnp.float32)
    if (c.pipeline_microbatches > 0 and mesh is not None
            and mesh.shape.get("pipe", 1) > 1):
        if moe:
            raise NotImplementedError(
                "MoE layers inside pipeline stages are not supported "
                "yet (the GPipe schedule carries no aux accumulator); "
                "use expert parallelism with pipe=1")
        if custom_positions:
            raise NotImplementedError(
                "pipeline parallelism assumes the default arange "
                "position layout (packed/offset positions differ per "
                "batch row; microbatches share one row)")
        if c.attention_impl == "ring":
            raise NotImplementedError(
                "attention_impl='ring' inside pipeline stages would "
                "nest shard_maps; use flash or dot with pipe > 1")
        from ray_tpu.parallel.pipeline import pipeline_layers

        # The block closes over batch-shaped sin/cos/positions; a
        # microbatch needs the broadcastable single-row versions, which
        # are only equivalent for the default arange layout.
        block = make_block(sin[:1], cos[:1], positions[:1])
        batch_axes = [a for a in ("data", "fsdp") if a in mesh.shape
                      and mesh.shape[a] > 1]
        x = pipeline_layers(
            lambda h, layer: block(h, layer), params["layers"], x,
            mesh=mesh, num_microbatches=c.pipeline_microbatches,
            batch_axes=batch_axes)
    else:
        block = make_block(sin, cos, positions)

        if moe:
            def scan_body(carry, layer_params):
                h, aux = carry
                h, aux_l = block(h, layer_params)
                return (h, aux + aux_l), None

            (x, aux_total), _ = jax.lax.scan(
                scan_body, (x, aux_total), params["layers"],
                unroll=c.scan_unroll)
        else:
            def scan_body(carry, layer_params):
                return block(carry, layer_params), None

            x, _ = jax.lax.scan(scan_body, x, params["layers"],
                                unroll=c.scan_unroll)

    x = rms_norm(x, params["final_norm"], c.norm_eps)
    if c.tie_embeddings:
        head = params["embed_tokens"].astype(c.dtype).T
    else:
        head = params["lm_head"].astype(c.dtype)
    logits = matmul(x, head)
    logits = with_logical_constraint(logits, "batch", "seq", "vocab")
    if return_aux:
        return logits, aux_total
    return logits


def loss_fn(params: PyTree, batch: Dict[str, jax.Array],
            config: LlamaConfig) -> jax.Array:
    """Mean next-token cross-entropy.  batch: tokens (B,S) int32,
    optional loss_mask (B,S)."""
    tokens = batch["tokens"]
    positions = batch.get("positions")
    aux = jnp.zeros((), jnp.float32)
    if positions is None:
        # Run the forward at the full sequence length and drop the last
        # position's logits, instead of slicing tokens to S-1: a
        # 2047-long sequence defeats the flash kernel's block tiling
        # (its fallback materializes S×S f32 scores — measured
        # 2.4s/step vs 1.4s on the 440M bench).
        logits, aux = forward(params, tokens, config, return_aux=True)
        logits = logits[:, :-1]
    else:
        # Packed/offset positions (dot-attention path): keep the old
        # S-1 slice so the last raw token never becomes a key — at full
        # length a small positions[S-1] (new-document start) would be
        # attended by every later-positioned query.
        logits, aux = forward(params, tokens[:, :-1], config,
                              positions=positions[:, :-1],
                              return_aux=True)
    targets = tokens[:, 1:]
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None],
                               axis=-1).squeeze(-1)
    nll = logz - gold
    mask = batch.get("loss_mask")
    if mask is None:
        ce = jnp.mean(nll)
    else:
        mask = mask[:, 1:].astype(jnp.float32)
        ce = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if config.moe_experts > 0:
        # Per-layer mean so the weight is depth-invariant.
        ce = ce + config.moe_aux_weight * aux / config.n_layers
    return ce


# ---------------------------------------------------------------------------
# Train step
# ---------------------------------------------------------------------------

def default_optimizer(learning_rate: float = 3e-4):
    import optax

    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(learning_rate, weight_decay=0.1),
    )


def init_train_state(rng: jax.Array, config: LlamaConfig,
                     optimizer=None,
                     fused: bool = False) -> Dict[str, Any]:
    """``fused=True`` pairs with ``make_train_step(fused=True)``: the
    opt_state is a ``FusedAdamWState`` instead of the optax chain
    tuple (same logical contents — count + two moment trees)."""
    params = init_params(rng, config)
    if fused:
        if optimizer is not None:
            raise ValueError("fused=True replaces the optax chain; "
                             "pass hyperparameters, not an optimizer")
        from ray_tpu.train.optim import fused_adamw_init

        opt_state = fused_adamw_init(params)
    else:
        if optimizer is None:
            optimizer = default_optimizer()
        opt_state = optimizer.init(params)
    return {
        "params": params,
        "opt_state": opt_state,
        "step": jnp.zeros((), jnp.int32),
    }


def make_train_step(config: LlamaConfig, optimizer=None,
                    donate: bool = True, fused: bool = False,
                    learning_rate: float = 3e-4) -> Callable:
    """Returns jitted ``train_step(state, batch) -> (state, metrics)``.

    Grad accumulation/clipping live in the optax chain; the step is a
    single XLA program — gradient psums over data/fsdp axes are inserted
    by the compiler from the shardings (no hand-written allreduce).

    ``fused=True`` replaces the optax chain with the single-pass fused
    AdamW (``train/optim.py``): identical hyperparameters and clip
    semantics as ``default_optimizer()``, ~6 tree passes fewer of
    param-sized HBM traffic in the optimizer slice of the step (the
    ``profile_mfu.py`` ``opt_overhead_s`` phase measures it).  Loss
    parity with the optax step is a tier-1 gate."""
    import optax

    if fused:
        if optimizer is not None:
            raise ValueError("fused=True replaces the optax chain; "
                             "pass hyperparameters, not an optimizer")
        from ray_tpu.train.optim import (fused_adamw_update,
                                         fused_hyperparams)

        hp = fused_hyperparams(learning_rate)

        def step(state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                state["params"], batch, config)
            params, opt_state, gnorm = fused_adamw_update(
                grads, state["opt_state"], state["params"], **hp)
            new_state = {"params": params, "opt_state": opt_state,
                         "step": state["step"] + 1}
            return new_state, {"loss": loss, "grad_norm": gnorm,
                               "step": new_state["step"]}

        return _annotate_step(
            jax.jit(step, donate_argnums=(0,) if donate else ()))

    if optimizer is None:
        optimizer = default_optimizer(learning_rate)

    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch,
                                                  config)
        updates, opt_state = optimizer.update(grads, state["opt_state"],
                                              state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1}
        gnorm = optax.global_norm(grads)
        return new_state, {"loss": loss, "grad_norm": gnorm,
                           "step": new_state["step"]}

    return _annotate_step(
        jax.jit(step, donate_argnums=(0,) if donate else ()))


class _AnnotatedStep:
    """Stamp each dispatch of the jitted train step with a
    ``jax.profiler.TraceAnnotation`` carrying the ambient trace id
    (observability/device.py): a device trace captured mid-training
    shows ``train.step#trace=<id>`` slices that correlate with the
    cluster timeline.  No-op cost when the device plane is disabled
    (shared nullcontext); everything else of the jitted program's
    surface (``lower``/``trace``/donation semantics) passes through
    untouched via delegation."""

    __slots__ = ("_jitted",)

    def __init__(self, jitted: Callable):
        self._jitted = jitted

    def __call__(self, state, batch):
        from ray_tpu.observability import device as _device

        with _device.annotation("train.step"):
            return self._jitted(state, batch)

    def __getattr__(self, name):
        return getattr(self._jitted, name)


def _annotate_step(jitted: Callable) -> Callable:
    return _AnnotatedStep(jitted)


# ---------------------------------------------------------------------------
# KV-cache decode (serving path)
# ---------------------------------------------------------------------------

def init_kv_cache(config: LlamaConfig, batch: int, max_len: int,
                  dtype: Any = None) -> Dict[str, jax.Array]:
    """Slot-structured KV cache for continuous batching: (L, B, S, Hkv,
    D) per tensor.  The serve replica owns one cache and admits
    requests into free batch slots (reference has no TPU decode loop to
    mirror; design follows the fixed-shape constraint of jit: cache
    shape and batch are static, per-slot positions are data)."""
    c = config
    dt = dtype or c.dtype
    shape = (c.n_layers, batch, max_len, c.n_kv_heads, c.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def init_paged_kv_cache(config: LlamaConfig, num_blocks: int,
                        block_size: int, dtype: Any = None,
                        kv_quant: Optional[str] = None
                        ) -> Dict[str, jax.Array]:
    """Block-pool KV cache for paged attention (vLLM SOSP '23 shape):
    ``(num_blocks, L, block_size, Hkv, D)`` per tensor.  BLOCK-major —
    one block's K (or V) across all layers is a single contiguous
    slab, so the prefill→decode KV handoff exports per-block zero-copy
    views (cluster/serialization.export_kv_blocks) instead of
    gathering.  Block 0 is reserved as the null/padding block: block
    tables pad with it, attention masks whatever it holds, and
    scatter-back writes land there harmlessly.  Memory scales with
    ``num_blocks`` (live tokens), not ``max_slots × max_len``.

    ``kv_quant`` ("int8"/"fp8", serve/kv_cache.KV_QUANT_FORMATS)
    stores blocks reduced-precision with one f32 scale per KV ROW —
    (block, layer, position, kv_head), ``k_scale``/``v_scale`` shaped
    ``(num_blocks, L, block_size, Hkv)`` — nearly halving the bytes
    per token (values drop 2 bytes → 1, scales add 4/head_dim), which
    the serving plane converts into ~2x the blocks (and therefore
    decode batch width) on the same pool budget.  The decode programs
    dequantize on gather and requantize on scatter
    (``quantize_kv_blocks``/``dequantize_kv_blocks``)."""
    c = config
    shape = (num_blocks, c.n_layers, block_size, c.n_kv_heads,
             c.head_dim)
    if kv_quant is None:
        dt = dtype or c.dtype
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    from ray_tpu.serve.kv_cache import kv_quant_info

    fmt = kv_quant_info(kv_quant)
    qdt = jnp.dtype(fmt.dtype_name)
    sshape = (num_blocks, c.n_layers, block_size, c.n_kv_heads)
    return {"k": jnp.zeros(shape, qdt), "v": jnp.zeros(shape, qdt),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32)}


def quantize_kv_blocks(x: jax.Array, qmax: float,
                       qdtype: Any) -> Tuple[jax.Array, jax.Array]:
    """Per-(block, layer, position, head) symmetric quantization of KV
    block updates.  x: (N, L, bs, Hkv, D) full precision; returns
    (stored (N, L, bs, Hkv, D) qdtype, scale (N, L, bs, Hkv) f32)
    with ``stored * scale ≈ x``.  One scale per KV ROW (amax over
    head_dim only): rope rotates K rows through position-dependent
    dynamic ranges, so row granularity cuts the error a further ~2-4x
    over per-block-per-head scales for 4/head_dim ≈ 3% extra bytes.
    The amax element maps exactly onto ``±qmax``, which makes
    dequantize→requantize a FIXED POINT: the decode loop re-scatters
    every gathered block each chunk (including untouched COW prefix
    blocks), and without that idempotence shared blocks would drift a
    little every chunk."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=4)
    scale = jnp.maximum(amax, 1e-30) / qmax
    q = xf / scale[..., None]
    if jnp.issubdtype(jnp.dtype(qdtype), jnp.integer):
        q = jnp.clip(jnp.round(q), -qmax, qmax)
    return q.astype(qdtype), scale


def dequantize_kv_blocks(stored: jax.Array, scale: jax.Array,
                         out_dtype: Any) -> jax.Array:
    """Inverse of :func:`quantize_kv_blocks` (same block layout)."""
    return (stored.astype(jnp.float32)
            * scale[..., None]).astype(out_dtype)


def prefill_forward(params: PyTree, tokens: jax.Array,
                    lengths: jax.Array, config: LlamaConfig
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Causal forward over right-padded prompts for cache insertion.

    tokens: (G, P) int32 right-padded prompts; lengths: (G,) real
    lengths.  Runs plain causal attention WITHIN each prompt (no cache
    read — massively cheaper than attending the full slot cache) and
    returns (last_logits (G, V), ks, vs) where ks/vs are (L, G, P,
    Hkv, D) ready to insert into slot caches and last_logits are the
    logits at each prompt's final real token (so the first generated
    token comes out of the prefill call itself — one less decode
    round-trip of TTFT).  Padding rows produce garbage K/V beyond
    lengths; the decode path overwrites each position before it first
    attends it, so they are never observed."""
    c = config
    G, P = tokens.shape
    dt = c.dtype
    x = params["embed_tokens"].astype(dt)[tokens]
    positions = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :],
                                 (G, P))
    sin, cos = rope_table(positions, c.head_dim, c.rope_theta)

    def body(x, layer):
        q, k, v = _qkv_rope(x, layer, sin, cos, c)
        attn = dot_attention(q, k, v, positions)
        x = _attn_out_mlp(x, attn, layer, c)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(lambda x, l: body(x, l), x,
                               params["layers"])
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1)  # (G,1,H)
    head = (params["embed_tokens"].astype(dt).T if c.tie_embeddings
            else params["lm_head"].astype(dt))
    last_logits = matmul(last, head)[:, 0]
    return last_logits, ks, vs


def insert_prefill(cache: Dict[str, jax.Array], ks: jax.Array,
                   vs: jax.Array, slots: jax.Array) -> Dict[str, jax.Array]:
    """Insert prefilled K/V rows into slot caches without per-slot
    scatters (XLA TPU serializes those): a one-hot slot projection
    spreads the group onto the batch axis, then a STATIC row-range
    select writes rows [0, P).  slots: (G,) int32; a negative slot
    drops that group member (partial-group padding)."""
    B = cache["k"].shape[1]
    P = ks.shape[2]
    onehot = (slots[:, None] ==
              jnp.arange(B, dtype=jnp.int32)[None, :])
    proj = onehot.astype(cache["k"].dtype)
    written = onehot.any(axis=0)[None, :, None, None, None]

    def ins(full, rows):
        spread = jnp.einsum("gb,lgphd->lbphd", proj,
                            rows.astype(full.dtype))
        cur = jax.lax.slice_in_dim(full, 0, P, axis=2)
        new = jnp.where(written, spread, cur)
        return jax.lax.dynamic_update_slice_in_dim(full, new, 0, axis=2)

    return {"k": ins(cache["k"], ks), "v": ins(cache["v"], vs)}


def _cache_attend(q, ck, cv, q_positions, scale):
    """q: (B, T, Hq, D); ck/cv: (B, S, Hkv, D); q_positions: (B, T).
    Causal against absolute cache positions: key j visible to query at
    position p iff j <= p."""
    B, T, Hq, D = q.shape
    S = ck.shape[1]
    Hkv = ck.shape[2]
    group = Hq // Hkv
    qg = q.reshape(B, T, Hkv, group, D)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, ck,
                        preferred_element_type=jnp.float32) * scale
    key_pos = jnp.arange(S, dtype=jnp.int32)
    mask = key_pos[None, None, None, None, :] <= \
        q_positions[:, None, None, :, None]
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, cv,
                     preferred_element_type=jnp.float32).astype(cv.dtype)
    return out.reshape(B, T, Hq, D)


def forward_with_cache(params: PyTree, tokens: jax.Array,
                       positions: jax.Array, cache: Dict[str, jax.Array],
                       config: LlamaConfig
                       ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Run T new tokens per slot against the cache.

    tokens: (B, T) int32; positions: (B, T) absolute positions (a
    slot's current length .. +T-1).  Writes the new K/V into the cache
    at those positions and returns (logits (B, T, V), new_cache).
    T=prompt_bucket for prefill, T=1 for decode — each T compiles
    once."""
    c = config
    if c.moe_experts > 0:
        raise NotImplementedError(
            "KV-cache decode for MoE models is not implemented yet; "
            "serve with a dense config")
    B, T = tokens.shape
    dt = c.dtype
    x = params["embed_tokens"].astype(dt)[tokens]
    sin, cos = rope_table(positions, c.head_dim, c.rope_theta)
    scale = c.head_dim ** -0.5

    def body(x, layer_and_cache):
        layer, ck_l, cv_l = layer_and_cache
        q, k, v = _qkv_rope(x, layer, sin, cos, c)

        # Scatter the T new K/V rows into each slot's cache at its own
        # positions (per-slot write offsets = data, shapes static).
        def write(cache_bslice, rows, pos0):
            return jax.lax.dynamic_update_slice(
                cache_bslice, rows, (pos0, jnp.int32(0), jnp.int32(0)))

        pos0 = positions[:, 0]
        ck_l = jax.vmap(write)(ck_l, k.astype(ck_l.dtype), pos0)
        cv_l = jax.vmap(write)(cv_l, v.astype(cv_l.dtype), pos0)

        attn = _cache_attend(q, ck_l, cv_l, positions, scale)
        x = _attn_out_mlp(x, attn, layer, c)
        return x, (ck_l, cv_l)

    def scan_body(x, inputs):
        x, new_cache = body(x, inputs)
        return x, new_cache

    x, (new_k, new_v) = jax.lax.scan(
        scan_body, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    head = (params["embed_tokens"].astype(dt).T if c.tie_embeddings
            else params["lm_head"].astype(dt))
    logits = matmul(x, head)
    return logits, {"k": new_k, "v": new_v}
