"""Model zoo (TPU-native, functional jax).

The reference ships no model implementations in its core (RLlib's
catalog is torch/tf); the TPU framework needs native models because
there is no external engine to delegate to (SURVEY.md §2.3).  Flagship:
Llama-family decoder LM (:mod:`ray_tpu.models.llama`) built
scan-over-layers with logical-axis shardings so one implementation
serves DP/FSDP/TP/SP/PP/EP via :mod:`ray_tpu.parallel` rule tables.
"""

from .llama import (
    LlamaConfig,
    init_params,
    param_logical_axes,
    forward,
    loss_fn,
    make_train_step,
    init_train_state,
)

__all__ = [
    "LlamaConfig",
    "init_params",
    "param_logical_axes",
    "forward",
    "loss_fn",
    "make_train_step",
    "init_train_state",
]
