"""RandomAccessDataset: O(log n) keyed lookups over a sorted dataset.

Reference: python/ray/data/random_access_dataset.py — the dataset is
sorted by key and partitioned over holder actors; a lookup binary-
searches the partition index and asks the owning actor, which binary-
searches its local blocks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


@ray_tpu.remote
class _BlockHolder:
    def __init__(self, blocks: List[Dict[str, np.ndarray]], key: str):
        self._key = key
        self._blocks = blocks
        self._lows = [float(np.asarray(b[key])[0]) for b in blocks]

    def get(self, key_value) -> Optional[Dict[str, Any]]:
        i = int(np.searchsorted(self._lows, key_value, side="right")) - 1
        for b in self._blocks[max(i, 0):i + 2]:
            col = np.asarray(b[self._key])
            j = int(np.searchsorted(col, key_value))
            if j < len(col) and col[j] == key_value:
                return {k: np.asarray(v)[j] for k, v in b.items()}
        return None

    def multiget(self, key_values: List) -> List[Optional[Dict]]:
        return [self.get(k) for k in key_values]


class RandomAccessDataset:
    """Built via ``Dataset.to_random_access_dataset(key)``."""

    def __init__(self, ds, key: str, *, num_workers: int = 2):
        self._key = key
        blocks = [dict(b) for b in ds.sort(key).iter_blocks()
                  if len(np.asarray(b[key]))]
        if not blocks:
            raise ValueError("cannot index an empty dataset")
        num_workers = max(1, min(num_workers, len(blocks)))
        shards: List[List] = [[] for _ in range(num_workers)]
        for i, b in enumerate(blocks):
            # Contiguous ranges per worker (blocks are globally sorted).
            shards[i * num_workers // len(blocks)].append(b)
        self._actors = [_BlockHolder.remote(s, key) for s in shards
                        if s]
        self._lows = [float(np.asarray(s[0][key])[0])
                      for s in shards if s]

    def _actor_for(self, key_value):
        i = int(np.searchsorted(self._lows, key_value,
                                side="right")) - 1
        return self._actors[max(i, 0)]

    def get_async(self, key_value):
        """ObjectRef resolving to the row dict (or None)."""
        return self._actor_for(key_value).get.remote(key_value)

    def multiget(self, key_values: List) -> List[Optional[Dict]]:
        by_actor: Dict[int, List] = {}
        order: Dict[int, List[int]] = {}
        for pos, kv in enumerate(key_values):
            a = self._actors.index(self._actor_for(kv))
            by_actor.setdefault(a, []).append(kv)
            order.setdefault(a, []).append(pos)
        out: List[Optional[Dict]] = [None] * len(key_values)
        refs = {a: self._actors[a].multiget.remote(kvs)
                for a, kvs in by_actor.items()}
        for a, ref in refs.items():
            for pos, row in zip(order[a], ray_tpu.get(ref)):
                out[pos] = row
        return out

    def destroy(self):
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []
