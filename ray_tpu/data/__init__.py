"""ray_tpu.data — streaming data engine feeding TPU training.

Reference: python/ray/data (81.3k LoC).  This is the TPU-first MVP of
the same shape: lazy Dataset plan → fused map phases → streaming
executor over ray_tpu tasks with backpressure → exact-size numpy
batches with device_put prefetch; ``streaming_split`` provides the
per-worker shards ray_tpu.train consumes (reference:
train/_internal/data_config.py).
"""

from .aggregate import (AggregateFn, Count, Max, Mean, Min, Std, Sum)
from .block import Block, BlockAccessor, BlockMetadata
from .context import DataContext
from .executor import ActorPoolStrategy
from .dataset import (DataIterator, Dataset, GroupedData, from_arrow,
                      from_blocks, from_items, from_numpy, from_pandas,
                      range, read_csv, read_datasource, read_images,
                      read_json, read_numpy, read_parquet,
                      read_tfrecords)
from .datasource import Datasource, FileDatasource, ReadTask
from .random_access import RandomAccessDataset
from . import preprocessors

__all__ = [
    "ActorPoolStrategy", "AggregateFn",
    "Block", "BlockAccessor", "BlockMetadata", "Count", "DataContext",
    "DataIterator", "Dataset", "Datasource", "FileDatasource",
    "GroupedData", "Max", "Mean", "Min",
    "RandomAccessDataset", "ReadTask", "Std", "Sum", "from_arrow",
    "from_blocks", "from_items", "from_numpy", "from_pandas",
    "preprocessors", "range", "read_csv", "read_datasource",
    "read_images", "read_json", "read_numpy", "read_parquet",
    "read_tfrecords",
]
