"""Fit/transform preprocessors chained into the dataset plan.

Reference: python/ray/data/preprocessor.py (base Preprocessor with
fit/transform/fit_transform over Datasets) + the concrete scalers and
encoders under python/ray/data/preprocessors/.  ``fit`` aggregates
statistics with one pass over the dataset; ``transform`` appends an
ordinary ``map_batches`` stage, so downstream training consumes the
preprocessed stream with no materialization (a preprocessor feeding
JaxTrainer is just another plan stage).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class Preprocessor:
    """fit(ds) → self (computes stats); transform(ds) → Dataset."""

    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted and type(self)._fit is not Preprocessor._fit:
            raise RuntimeError(
                f"{type(self).__name__} must be fit before transform")
        return ds.map_batches(self._transform_batch)

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    # Stateless preprocessors override only _transform_batch.
    def _fit(self, ds) -> None:
        pass

    def _transform_batch(self, batch: Dict[str, np.ndarray]):
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference:
    preprocessors/scaler.py StandardScaler)."""

    def __init__(self, columns: Sequence[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        n = 0
        s = {c: 0.0 for c in self.columns}
        sq = {c: 0.0 for c in self.columns}
        for block in ds.iter_blocks():
            for c in self.columns:
                v = np.asarray(block[c], dtype=np.float64)
                s[c] += float(v.sum())
                sq[c] += float((v * v).sum())
            n += len(np.asarray(block[self.columns[0]]))
        for c in self.columns:
            mean = s[c] / max(n, 1)
            var = max(sq[c] / max(n, 1) - mean * mean, 0.0)
            self.stats_[c] = (mean, float(np.sqrt(var)) or 1.0)

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            out[c] = (np.asarray(batch[c], np.float64) - mean) / std
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (reference MinMaxScaler)."""

    def __init__(self, columns: Sequence[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds) -> None:
        lo = {c: np.inf for c in self.columns}
        hi = {c: -np.inf for c in self.columns}
        for block in ds.iter_blocks():
            for c in self.columns:
                v = np.asarray(block[c], dtype=np.float64)
                lo[c] = min(lo[c], float(v.min()))
                hi[c] = max(hi[c], float(v.max()))
        for c in self.columns:
            self.stats_[c] = (lo[c], hi[c])

    def _transform_batch(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats_[c]
            span = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return out


class LabelEncoder(Preprocessor):
    """Categorical column → dense int codes (reference LabelEncoder)."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[List] = None

    def _fit(self, ds) -> None:
        seen = set()
        for block in ds.iter_blocks():
            seen.update(np.asarray(block[self.label_column]).tolist())
        self.classes_ = sorted(seen)

    def _transform_batch(self, batch):
        out = dict(batch)
        index = {v: i for i, v in enumerate(self.classes_)}
        out[self.label_column] = np.asarray(
            [index[v] for v in
             np.asarray(batch[self.label_column]).tolist()],
            dtype=np.int64)
        return out


class Concatenator(Preprocessor):
    """Merge feature columns into one float matrix column (reference
    preprocessors/concatenator.py) — the shape a train step consumes."""

    def __init__(self, columns: Sequence[str],
                 output_column_name: str = "concat_out",
                 dtype=np.float32):
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _transform_batch(self, batch):
        out = {k: v for k, v in batch.items() if k not in self.columns}
        cols = [np.asarray(batch[c], self.dtype).reshape(
            len(np.asarray(batch[c])), -1) for c in self.columns]
        out[self.output_column_name] = np.concatenate(cols, axis=1)
        return out


class Chain(Preprocessor):
    """Apply preprocessors in order; fit runs sequentially with each
    stage fitting on the PREVIOUS stages' transformed output
    (reference preprocessors/chain.py)."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)

    def fit(self, ds) -> "Chain":
        cur = ds
        for st in self.stages:
            st.fit(cur)
            cur = st.transform(cur)
        self._fitted = True
        return self

    def transform(self, ds):
        cur = ds
        for st in self.stages:
            cur = st.transform(cur)
        return cur

    def _transform_batch(self, batch):
        for st in self.stages:
            batch = st._transform_batch(batch)
        return batch
