"""Combinable aggregate functions for ``groupby``/``aggregate``.

Reference: python/ray/data/aggregate.py (``AggregateFn`` with
init/accumulate/merge/finalize, applied row-at-a-time).  Redesign for
the numpy engine: every phase is vectorized over *runs* of equal keys
in a key-sorted block (``np.ufunc.reduceat`` over run boundaries), and
the partial states are themselves blocks — so they ride the push
exchange like any other fragment and reducers can combine them
incrementally without holding raw rows.

Three phases per aggregate:

- ``partial(block, bounds)``  — map side: per-run state arrays from raw
  rows (one state row per distinct key in the fragment);
- ``combine(states, bounds)`` — reduce side: merge state rows after the
  reducer re-sorts concatenated partials by key (runs again);
- ``finalize(states)``        — the output column.

NaN semantics follow naive numpy (``sum`` over a group containing NaN
is NaN); NaN *keys* form a single group (see block.stable_hash_column /
group_boundaries).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .block import (Block, BlockAccessor, group_boundaries,
                    hash_partition_indices, sort_by_key)

# Synthetic key column for whole-dataset aggregation (one global
# group); stripped from the finalized output.
GLOBAL_KEY = "__global__"


def _reduceat(ufunc, col: np.ndarray, bounds: np.ndarray) -> np.ndarray:
    """Per-run reduction of ``col`` over boundary offsets ``bounds``
    (``[0, s1, ..., n]``).  Empty input → empty output."""
    if len(bounds) <= 1:
        return col[:0]
    return ufunc.reduceat(col, bounds[:-1])


class AggregateFn:
    """One combinable aggregate.  ``fields`` names the per-group state
    columns; the exchange prefixes them per slot so several aggregates
    share one state block."""

    fields = ()
    kind = "agg"

    def __init__(self, on: Optional[str] = None):
        self.on = on

    def out_name(self) -> str:
        return f"{self.kind}({self.on if self.on is not None else ''})"

    def _col(self, block) -> np.ndarray:
        if self.on is None:
            raise ValueError(f"{self.kind}() requires on=<column>")
        if self.on not in block:
            raise KeyError(
                f"aggregate column {self.on!r} not in block columns "
                f"{sorted(block.keys())}")
        return block[self.on]

    def partial(self, block, bounds) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def combine(self, states: Dict[str, np.ndarray],
                bounds) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def finalize(self, states: Dict[str, np.ndarray]) -> np.ndarray:
        raise NotImplementedError


class Count(AggregateFn):
    kind = "count"
    fields = ("n",)

    def __init__(self):
        super().__init__(on=None)

    def partial(self, block, bounds):
        return {"n": np.diff(bounds).astype(np.int64)}

    def combine(self, states, bounds):
        return {"n": _reduceat(np.add, states["n"], bounds)}

    def finalize(self, states):
        return states["n"]


class Sum(AggregateFn):
    kind = "sum"
    fields = ("s",)

    def partial(self, block, bounds):
        return {"s": _reduceat(np.add, self._col(block), bounds)}

    def combine(self, states, bounds):
        return {"s": _reduceat(np.add, states["s"], bounds)}

    def finalize(self, states):
        return states["s"]


class Min(AggregateFn):
    kind = "min"
    fields = ("m",)

    def partial(self, block, bounds):
        return {"m": _reduceat(np.minimum, self._col(block), bounds)}

    def combine(self, states, bounds):
        return {"m": _reduceat(np.minimum, states["m"], bounds)}

    def finalize(self, states):
        return states["m"]


class Max(AggregateFn):
    kind = "max"
    fields = ("m",)

    def partial(self, block, bounds):
        return {"m": _reduceat(np.maximum, self._col(block), bounds)}

    def combine(self, states, bounds):
        return {"m": _reduceat(np.maximum, states["m"], bounds)}

    def finalize(self, states):
        return states["m"]


class Mean(AggregateFn):
    kind = "mean"
    fields = ("s", "n")

    def partial(self, block, bounds):
        col = self._col(block).astype(np.float64, copy=False)
        return {"s": _reduceat(np.add, col, bounds),
                "n": np.diff(bounds).astype(np.int64)}

    def combine(self, states, bounds):
        return {"s": _reduceat(np.add, states["s"], bounds),
                "n": _reduceat(np.add, states["n"], bounds)}

    def finalize(self, states):
        return states["s"] / states["n"]


class Std(AggregateFn):
    """Population / sample std via (sum, sum-of-squares, n) moments in
    float64 — combinable with plain addition, accurate to well past the
    parity tests' tolerance for non-pathological data."""

    kind = "std"
    fields = ("s", "ss", "n")

    def __init__(self, on: Optional[str] = None, ddof: int = 0):
        super().__init__(on=on)
        self.ddof = ddof

    def partial(self, block, bounds):
        col = self._col(block).astype(np.float64, copy=False)
        return {"s": _reduceat(np.add, col, bounds),
                "ss": _reduceat(np.add, col * col, bounds),
                "n": np.diff(bounds).astype(np.int64)}

    def combine(self, states, bounds):
        return {k: _reduceat(np.add, states[k], bounds)
                for k in self.fields}

    def finalize(self, states):
        n = states["n"].astype(np.float64)
        mean = states["s"] / n
        var = states["ss"] / n - mean * mean
        denom = n - self.ddof
        with np.errstate(invalid="ignore", divide="ignore"):
            out = np.sqrt(np.clip(var, 0.0, None) * (n / denom))
        out[denom <= 0] = np.nan
        return out


_BY_NAME = {c.kind: c for c in (Count, Sum, Min, Max, Mean, Std)}


def resolve_aggregate(spec) -> AggregateFn:
    """Accept an AggregateFn instance, a ``"count"`` style name, or a
    ``("sum", "col")`` tuple (the forms ``Dataset.aggregate`` takes)."""
    if isinstance(spec, AggregateFn):
        return spec
    if isinstance(spec, str):
        if spec not in _BY_NAME:
            raise ValueError(
                f"unknown aggregate {spec!r}; one of {sorted(_BY_NAME)}")
        return _BY_NAME[spec]() if spec == "count" else _BY_NAME[spec](None)
    if isinstance(spec, (tuple, list)) and len(spec) == 2:
        name, on = spec
        if name not in _BY_NAME:
            raise ValueError(
                f"unknown aggregate {name!r}; one of {sorted(_BY_NAME)}")
        return _BY_NAME[name]() if name == "count" else _BY_NAME[name](on)
    raise TypeError(
        f"aggregate spec must be AggregateFn | name | (name, col), "
        f"got {spec!r}")


# ---------------------------------------------------------------------------
# Exchange plumbing: partial-state blocks + the reducers' combine
# ---------------------------------------------------------------------------

def partial_state_block(block: Block, key: Optional[str],
                        aggs: List[AggregateFn]) -> Block:
    """Map-side partial aggregation of one raw block: one state row
    per distinct key in the block — the only thing that rides the
    shuffle for an aggregate exchange."""
    if key is None:
        n = BlockAccessor.num_rows(block)
        bounds = np.array([0, n] if n else [0], dtype=np.int64)
        sb = block
        state: Block = {GLOBAL_KEY: np.zeros(1 if n else 0, np.int64)}
    else:
        sb = sort_by_key(block, key)
        bounds = group_boundaries(sb[key])
        state = {key: sb[key][bounds[:-1]]}
    for i, agg in enumerate(aggs):
        for f, arr in agg.partial(sb, bounds).items():
            state[f"__s{i}_{f}"] = np.asarray(arr)
    return state


def make_agg_partition(key: Optional[str], aggs: List[AggregateFn]):
    """Exchange ``partition_fn``: partial-aggregate the block, then
    hash-partition the state rows by key so every partial of one key
    lands on one reducer."""
    kcol = key if key is not None else GLOBAL_KEY

    def partition(block: Block, n: int, _spec, _offset: int):
        state = partial_state_block(block, key, aggs)
        if not BlockAccessor.num_rows(state):
            return []
        idx = hash_partition_indices(state, kcol, n)
        return [(j, BlockAccessor.take(state, np.nonzero(idx == j)[0]))
                for j in range(n)]

    return partition


class AggCombine:
    """The reducers' incremental-combine mode for aggregate
    exchanges: ``add`` folds arriving partial-state fragments into the
    partition's running state (re-sorted by key, runs combined), and
    ``finalize`` emits the output columns.  Raw rows never reach the
    reducer."""

    def __init__(self, key: Optional[str], aggs: List[AggregateFn]):
        self.key = key if key is not None else GLOBAL_KEY
        self.aggs = list(aggs)

    def add(self, state: Optional[Block],
            blocks: List[Block]) -> Block:
        parts = ([state] if state else []) + \
            [b for b in blocks if BlockAccessor.num_rows(b)]
        if not parts:
            return state if state is not None else {}
        whole = BlockAccessor.concat(parts)
        sb = sort_by_key(whole, self.key)
        bounds = group_boundaries(sb[self.key])
        out: Block = {self.key: sb[self.key][bounds[:-1]]}
        for i, agg in enumerate(self.aggs):
            states = {f: sb[f"__s{i}_{f}"] for f in agg.fields}
            for f, arr in agg.combine(states, bounds).items():
                out[f"__s{i}_{f}"] = np.asarray(arr)
        return out

    def finalize(self, state: Optional[Block], _spec,
                 _part_idx: int) -> List[Block]:
        if state is None or not BlockAccessor.num_rows(state):
            return []
        out: Block = {}
        if self.key != GLOBAL_KEY:
            out[self.key] = state[self.key]
        for i, agg in enumerate(self.aggs):
            out[agg.out_name()] = np.asarray(agg.finalize(
                {f: state[f"__s{i}_{f}"] for f in agg.fields}))
        return [out]
