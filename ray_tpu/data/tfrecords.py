"""TFRecord + tf.train.Example support, dependency-free.

Reference: python/ray/data reads/writes TFRecords through tensorflow
(datasource/tfrecords_datasource.py).  Here both the record FRAMING
and the Example protobuf codec are implemented natively so worker
processes never import tensorflow (a multi-second, memory-heavy import
on the data path); the test suite cross-checks round-trips against
tensorflow itself.

Wire formats:
- TFRecord framing: [len u64le][masked-crc32c(len) u32le][data]
  [masked-crc32c(data) u32le].
- tf.train.Example proto: Example{1: Features{1: map<string,
  Feature>}}, Feature = oneof {1: BytesList, 2: FloatList,
  3: Int64List}, each list packing its values in field 1.
"""

from __future__ import annotations

import os
import struct
from typing import Any, Dict, Iterable, List

import numpy as np

# ---------------------------------------------------------------------------
# crc32c (Castagnoli), table-driven + TFRecord masking
# ---------------------------------------------------------------------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Record framing
# ---------------------------------------------------------------------------

def write_records(path: str, records: Iterable[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            hdr = struct.pack("<Q", len(rec))
            f.write(hdr)
            f.write(struct.pack("<I", _masked_crc(hdr)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))
            n += 1
    return n


def read_records(path: str, *, verify: bool = False) -> Iterable[bytes]:
    """Yield raw record payloads.  CRC verification is optional — the
    length CRC is always checked (it guards framing desync), the data
    CRC only under ``verify`` (a full-file pure-python crc pass)."""
    with open(path, "rb") as f:
        while True:
            hdr = f.read(8)
            if not hdr:
                return
            if len(hdr) != 8:
                raise ValueError(f"truncated TFRecord header in {path}")
            (crc,) = struct.unpack("<I", f.read(4))
            if _masked_crc(hdr) != crc:
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            (length,) = struct.unpack("<Q", hdr)
            data = f.read(length)
            if len(data) != length:
                raise ValueError(f"truncated TFRecord data in {path}")
            (dcrc,) = struct.unpack("<I", f.read(4))
            if verify and _masked_crc(data) != dcrc:
                raise ValueError(f"corrupt TFRecord data crc in {path}")
            yield data


# ---------------------------------------------------------------------------
# Minimal protobuf wire codec for tf.train.Example
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: memoryview, pos: int):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field (wire type 2)."""
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _encode_feature(value) -> bytes:
    """One tf.train.Feature from a numpy array / bytes / str / scalar."""
    if isinstance(value, (bytes, bytearray)):
        inner = _ld(1, bytes(value))
        return _ld(1, inner)                      # BytesList in field 1
    if isinstance(value, str):
        return _encode_feature(value.encode())
    arr = np.asarray(value)
    if arr.dtype.kind in ("S", "U", "O"):
        items = b"".join(
            _ld(1, (v.encode() if isinstance(v, str) else bytes(v)))
            for v in arr.reshape(-1))
        return _ld(1, items)
    if arr.dtype.kind == "f":
        packed = arr.reshape(-1).astype("<f4").tobytes()
        return _ld(2, _ld(1, packed))             # FloatList, packed
    if arr.dtype.kind in ("i", "u", "b"):
        ints = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                        for v in arr.reshape(-1))
        return _ld(3, _ld(1, ints))               # Int64List, packed
    raise TypeError(
        f"cannot encode dtype {arr.dtype} as a tf.train.Feature")


def encode_example(row: Dict[str, Any]) -> bytes:
    entries = b"".join(
        _ld(1, _ld(1, k.encode()) + _ld(2, _encode_feature(v)))
        for k, v in row.items())
    return _ld(1, entries)                        # Example{features=1}


def _decode_list(kind: int, payload: memoryview):
    """Decode BytesList/FloatList/Int64List field-1 contents."""
    pos = 0
    if kind == 1:                                 # bytes
        out_b: List[bytes] = []
        while pos < len(payload):
            tag, pos = _read_varint(payload, pos)
            ln, pos = _read_varint(payload, pos)
            out_b.append(bytes(payload[pos:pos + ln]))
            pos += ln
        return out_b
    if kind == 2:                                 # float
        vals: List[float] = []
        while pos < len(payload):
            tag, pos = _read_varint(payload, pos)
            if tag & 7 == 2:                      # packed
                ln, pos = _read_varint(payload, pos)
                vals.extend(np.frombuffer(
                    payload[pos:pos + ln], dtype="<f4").tolist())
                pos += ln
            else:                                 # unpacked fixed32
                vals.append(struct.unpack(
                    "<f", payload[pos:pos + 4])[0])
                pos += 4
        return np.asarray(vals, dtype=np.float32)
    ints: List[int] = []
    while pos < len(payload):
        tag, pos = _read_varint(payload, pos)
        if tag & 7 == 2:                          # packed varints
            ln, pos = _read_varint(payload, pos)
            end = pos + ln
            while pos < end:
                v, pos = _read_varint(payload, pos)
                if v >= 1 << 63:
                    v -= 1 << 64
                ints.append(v)
        else:
            v, pos = _read_varint(payload, pos)
            if v >= 1 << 63:
                v -= 1 << 64
            ints.append(v)
    return np.asarray(ints, dtype=np.int64)


def _walk_fields(buf: memoryview):
    """Yield (field_number, wire_type, value) over a message."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 2:
            ln, pos = _read_varint(buf, pos)
            yield field, wt, buf[pos:pos + ln]
            pos += ln
        elif wt == 0:
            v, pos = _read_varint(buf, pos)
            yield field, wt, v
        elif wt == 5:
            yield field, wt, buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            yield field, wt, buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")


def decode_example(data: bytes) -> Dict[str, Any]:
    """tf.train.Example bytes → {name: np.ndarray | list[bytes]}.
    Single-element lists are unwrapped to scalars/0-d values to mirror
    the reference reader's row shape."""
    out: Dict[str, Any] = {}
    buf = memoryview(data)
    for field, _wt, features in _walk_fields(buf):
        if field != 1:
            continue
        for f2, _w2, entry in _walk_fields(features):
            if f2 != 1:
                continue
            key = None
            value = None
            for f3, _w3, v in _walk_fields(entry):
                if f3 == 1:
                    key = bytes(v).decode()
                elif f3 == 2:
                    for kind, _w4, payload in _walk_fields(v):
                        value = _decode_list(kind, payload)
            if key is not None and value is not None:
                if isinstance(value, list):
                    out[key] = value[0] if len(value) == 1 else value
                elif getattr(value, "shape", None) == (1,):
                    out[key] = value[0]
                else:
                    out[key] = value
    return out


# ---------------------------------------------------------------------------
# Datasource / writer glue
# ---------------------------------------------------------------------------

def read_tfrecords_file(path: str) -> List[Dict[str, Any]]:
    from .block import BlockAccessor

    rows = [decode_example(rec) for rec in read_records(path)]
    return [BlockAccessor.from_rows(rows)] if rows else []


def write_tfrecords_file(path: str, blocks) -> int:
    from .block import BlockAccessor

    def rows():
        for b in blocks:
            for row in BlockAccessor.to_rows(b):
                yield encode_example(row)

    return write_records(path, rows())
