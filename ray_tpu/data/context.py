"""Global data-engine tunables.

Reference: python/ray/data/context.py:180 (``DataContext`` — target block
size, concurrency caps, eager-free flags).  Kept deliberately small: the
knobs the TPU input pipeline actually needs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class DataContext:
    # Rows per block the engine aims for when it has a choice (reads /
    # repartition defaults).  Reference targets bytes; rows are the more
    # natural unit when blocks feed fixed-shape jax batches.
    target_block_rows: int = 4096
    # Max concurrently running block tasks per map phase (backpressure
    # cap; reference: execution/backpressure_policy/
    # concurrency_cap_backpressure_policy.py).
    max_concurrency: int = field(
        default_factory=lambda: min(8, os.cpu_count() or 8))
    # Completed-but-not-yet-consumed blocks the executor will hold while
    # preserving order before it stops dispatching (reference:
    # streaming_executor_state.py:533 backpressure-aware op choice).
    output_buffer_blocks: int = 16
    # Batches the iterator prefetches ahead of the consumer
    # (reference: _internal/batcher.py + iter_batches prefetch_batches).
    prefetch_batches: int = 2
    # Seconds between executor wait() polls (also the cadence at which
    # new work is dispatched when nothing completes).
    wait_timeout_s: float = 0.05
    # -- push exchange (data/exchange.py) -----------------------------
    # Map-side coalescing: fragments buffered per reducer flush once
    # they reach this many bytes (one ring frame / one push per flush).
    shuffle_fragment_bytes: int = 1 << 20
    # Reducer memory limit per reduce partition: buffered fragments
    # beyond this spill to plasma (which LRU-spills to disk under its
    # own pressure), so a reduce partition can outgrow memory.
    shuffle_spill_limit_bytes: int = 128 << 20
    # Ring slots per mapper-process -> reducer shm channel.
    shuffle_ring_slots: int = 16
    # Deadline for all pushed fragments to land at the reducers after
    # the map stage completes (a dead transport surfaces typed instead
    # of hanging the exchange).
    shuffle_timeout_s: float = 120.0
    # Cap on reducer actors per exchange (each owns
    # ceil(n_out / reducers) output partitions).
    shuffle_reducers: int = field(
        default_factory=lambda: min(8, os.cpu_count() or 8))

    _global: "DataContext" = None  # type: ignore[assignment]

    @staticmethod
    def get_current() -> "DataContext":
        if DataContext._global is None:
            DataContext._global = DataContext()
        return DataContext._global
