"""Columnar block model.

Reference: python/ray/data/block.py:59 defines ``Block = Union[pyarrow.Table,
pandas.DataFrame]`` with a ``BlockAccessor`` (block.py:232) dispatching on the
concrete type.  TPU-first redesign: the canonical block here is a plain
``dict[str, np.ndarray]`` — the exact shape a jax train step consumes, so the
path block → batch → ``jax.device_put`` is zero-conversion.  Arrow tables and
pandas frames are converted *at the edge* (read / from_pandas) instead of
being threaded through the whole engine.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

# A block is a dict of equal-length numpy arrays (first axis = rows).
Block = Dict[str, np.ndarray]


def _as_array(v: Any) -> np.ndarray:
    a = np.asarray(v)
    if a.dtype == object:
        # Keep object arrays (ragged / str mixes) — numpy-native engine
        # still supports them, they just can't feed the TPU directly.
        return a
    return a


class BlockAccessor:
    """Stateless helpers over the canonical block type.

    Mirrors the role of reference ``BlockAccessor`` (data/block.py:232):
    every structural operation the engine needs, in one place.
    """

    @staticmethod
    def num_rows(block: Block) -> int:
        if not block:
            return 0
        return len(next(iter(block.values())))

    @staticmethod
    def size_bytes(block: Block) -> int:
        total = 0
        for col in block.values():
            if col.dtype == object:
                total += sum(len(str(x)) for x in col) + col.nbytes
            else:
                total += col.nbytes
        return total

    @staticmethod
    def schema(block: Block) -> Dict[str, np.dtype]:
        return {k: v.dtype for k, v in block.items()}

    @staticmethod
    def slice(block: Block, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in block.items()}

    @staticmethod
    def take(block: Block, indices: np.ndarray) -> Block:
        return {k: v[indices] for k, v in block.items()}

    @staticmethod
    def concat(blocks: Sequence[Block]) -> Block:
        """Concatenate blocks.  Blocks are immutable by contract
        (transform fns must return new arrays, never mutate inputs):
        single-block concat and slice() return aliases/views, so an
        in-place mutation downstream would corrupt upstream blocks."""
        blocks = [b for b in blocks if BlockAccessor.num_rows(b)]
        if not blocks:
            return {}
        if len(blocks) == 1:  # no copy for the common single-block case
            return blocks[0]
        keys = list(blocks[0].keys())
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}

    @staticmethod
    def from_rows(rows: Sequence[Any]) -> Block:
        """Build a block from user 'row' objects.

        Scalars / arrays become an ``"item"`` column (reference uses the
        same convention for simple datasets, data/_internal/numpy ops);
        dict rows become columns.
        """
        if not rows:
            return {}
        first = rows[0]
        if isinstance(first, dict):
            keys = list(first.keys())
            out: Block = {}
            for k in keys:
                vals = [r[k] for r in rows]
                out[k] = _stack(vals)
            return out
        return {"item": _stack(list(rows))}

    @staticmethod
    def to_rows(block: Block) -> List[Dict[str, Any]]:
        n = BlockAccessor.num_rows(block)
        keys = list(block.keys())
        return [{k: block[k][i] for k in keys} for i in range(n)]

    @staticmethod
    def from_pandas(df) -> Block:
        return {str(c): _as_array(df[c].to_numpy()) for c in df.columns}

    @staticmethod
    def to_pandas(block: Block):
        import pandas as pd

        return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                             for k, v in block.items()})

    @staticmethod
    def from_arrow(table) -> Block:
        out: Block = {}
        for name in table.column_names:
            col = table.column(name)
            try:
                out[name] = _as_array(col.to_numpy(zero_copy_only=False))
            except Exception:
                out[name] = np.array(col.to_pylist(), dtype=object)
        return out

    @staticmethod
    def validate(block: Block) -> Block:
        if not isinstance(block, dict):
            raise TypeError(
                f"a block must be a dict of numpy arrays, got {type(block)}"
                " — map_batches fns must return dict[str, array-like]")
        out = {k: _as_array(v) for k, v in block.items()}
        lengths = {k: len(v) for k, v in out.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"ragged block columns: {lengths}")
        return out


# -- stable hashing for shuffle partitioning ---------------------------
#
# Python's builtin ``hash`` is salted per process (PYTHONHASHSEED), so a
# mapper and a reducer in different workers would disagree on which
# partition owns a key.  The exchange needs a hash that is stable across
# processes and hosts: crc32 for strings/objects, a Knuth
# multiplicative mix for numerics.  NaN keys canonicalize to one bucket
# (NaN != NaN, but groupby treats all NaNs as one group) and -0.0
# hashes with +0.0.

_HASH_MIX = np.uint64(0x9E3779B97F4A7C15)


def stable_hash_column(col: np.ndarray) -> np.ndarray:
    """Per-row uint64 hashes of a key column, identical in every
    process.  Vectorized for numeric dtypes; object/str columns go
    through crc32 row-wise."""
    import zlib

    if col.dtype == object or col.dtype.kind in "US":
        out = np.empty(len(col), dtype=np.uint64)
        for i, v in enumerate(col):
            if isinstance(v, float) and v != v:  # NaN object key
                out[i] = np.uint64(0x7FF8000000000000)
                continue
            out[i] = np.uint64(
                zlib.crc32(str(v).encode("utf-8", "surrogatepass")))
        bits = out
    elif col.dtype.kind == "f":
        f = col.astype(np.float64, copy=True)
        f[f == 0.0] = 0.0  # -0.0 -> +0.0 so both hash alike
        bits = f.view(np.uint64).copy()
        bits[np.isnan(f)] = np.uint64(0x7FF8000000000000)  # one NaN bucket
    elif col.dtype.kind == "b":
        bits = col.astype(np.uint64)
    else:  # signed/unsigned ints
        bits = col.astype(np.int64, copy=False).view(np.uint64).copy()
    with np.errstate(over="ignore"):
        h = bits * _HASH_MIX
        h ^= h >> np.uint64(29)
        h *= np.uint64(0xBF58476D1CE4E5B9)
        h ^= h >> np.uint64(32)
    return h


def hash_partition_indices(block: Block, key: str, n: int) -> np.ndarray:
    """Row -> partition index in ``[0, n)`` by stable key hash."""
    if key not in block:
        raise KeyError(
            f"groupby/shuffle key {key!r} not in block columns "
            f"{sorted(block.keys())}")
    return (stable_hash_column(block[key]) % np.uint64(n)).astype(np.int64)


def sort_by_key(block: Block, key: str) -> Block:
    """Stable-sort a block's rows by key, NaNs last (numpy argsort
    convention), so equal keys form contiguous runs for segment
    reduction."""
    col = block[key]
    if col.dtype == object:
        order = np.argsort(
            np.array([_obj_sort_token(v) for v in col]), kind="stable")
    else:
        order = np.argsort(col, kind="stable")
    return BlockAccessor.take(block, order)


def _obj_sort_token(v: Any) -> str:
    if isinstance(v, float) and v != v:
        return "￿￿NaN"  # after any realistic string
    return str(v)


def group_boundaries(col: np.ndarray) -> np.ndarray:
    """Start offsets of equal-key runs in a key-sorted column, plus the
    terminal length — ``[0, s1, ..., n]`` ready for pairwise slicing or
    ``np.add.reduceat``.  All NaNs count as one run."""
    n = len(col)
    if n == 0:
        return np.array([0], dtype=np.int64)
    if col.dtype.kind == "f":
        nan = np.isnan(col)
        neq = col[1:] != col[:-1]
        neq &= ~(nan[1:] & nan[:-1])  # NaN run stays one group
    elif col.dtype == object:
        neq = np.array([_obj_key_ne(col[i], col[i + 1])
                        for i in range(n - 1)], dtype=bool)
    else:
        neq = col[1:] != col[:-1]
    starts = np.flatnonzero(neq) + 1
    return np.concatenate(([0], starts, [n])).astype(np.int64)


def _obj_key_ne(a: Any, b: Any) -> bool:
    a_nan = isinstance(a, float) and a != a
    b_nan = isinstance(b, float) and b != b
    if a_nan or b_nan:
        return not (a_nan and b_nan)
    return a != b


def _stack(vals: List[Any]) -> np.ndarray:
    first = np.asarray(vals[0])
    if first.dtype != object and first.ndim > 0:
        try:
            return np.stack([np.asarray(v) for v in vals])
        except ValueError:
            pass  # ragged → object column
    arr = np.empty(len(vals), dtype=object) if (
        first.dtype == object or first.ndim > 0) else None
    if arr is not None:
        for i, v in enumerate(vals):
            arr[i] = v
        return arr
    return np.asarray(vals)


class BlockMetadata:
    """Per-block bookkeeping carried alongside the ObjectRef
    (reference: data/block.py BlockMetadata)."""

    __slots__ = ("num_rows", "size_bytes")

    def __init__(self, num_rows: int, size_bytes: int):
        self.num_rows = num_rows
        self.size_bytes = size_bytes

    @staticmethod
    def of(block: Block) -> "BlockMetadata":
        return BlockMetadata(BlockAccessor.num_rows(block),
                             BlockAccessor.size_bytes(block))
