"""Columnar block model.

Reference: python/ray/data/block.py:59 defines ``Block = Union[pyarrow.Table,
pandas.DataFrame]`` with a ``BlockAccessor`` (block.py:232) dispatching on the
concrete type.  TPU-first redesign: the canonical block here is a plain
``dict[str, np.ndarray]`` — the exact shape a jax train step consumes, so the
path block → batch → ``jax.device_put`` is zero-conversion.  Arrow tables and
pandas frames are converted *at the edge* (read / from_pandas) instead of
being threaded through the whole engine.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

# A block is a dict of equal-length numpy arrays (first axis = rows).
Block = Dict[str, np.ndarray]


def _as_array(v: Any) -> np.ndarray:
    a = np.asarray(v)
    if a.dtype == object:
        # Keep object arrays (ragged / str mixes) — numpy-native engine
        # still supports them, they just can't feed the TPU directly.
        return a
    return a


class BlockAccessor:
    """Stateless helpers over the canonical block type.

    Mirrors the role of reference ``BlockAccessor`` (data/block.py:232):
    every structural operation the engine needs, in one place.
    """

    @staticmethod
    def num_rows(block: Block) -> int:
        if not block:
            return 0
        return len(next(iter(block.values())))

    @staticmethod
    def size_bytes(block: Block) -> int:
        total = 0
        for col in block.values():
            if col.dtype == object:
                total += sum(len(str(x)) for x in col) + col.nbytes
            else:
                total += col.nbytes
        return total

    @staticmethod
    def schema(block: Block) -> Dict[str, np.dtype]:
        return {k: v.dtype for k, v in block.items()}

    @staticmethod
    def slice(block: Block, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in block.items()}

    @staticmethod
    def take(block: Block, indices: np.ndarray) -> Block:
        return {k: v[indices] for k, v in block.items()}

    @staticmethod
    def concat(blocks: Sequence[Block]) -> Block:
        """Concatenate blocks.  Blocks are immutable by contract
        (transform fns must return new arrays, never mutate inputs):
        single-block concat and slice() return aliases/views, so an
        in-place mutation downstream would corrupt upstream blocks."""
        blocks = [b for b in blocks if BlockAccessor.num_rows(b)]
        if not blocks:
            return {}
        if len(blocks) == 1:  # no copy for the common single-block case
            return blocks[0]
        keys = list(blocks[0].keys())
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}

    @staticmethod
    def from_rows(rows: Sequence[Any]) -> Block:
        """Build a block from user 'row' objects.

        Scalars / arrays become an ``"item"`` column (reference uses the
        same convention for simple datasets, data/_internal/numpy ops);
        dict rows become columns.
        """
        if not rows:
            return {}
        first = rows[0]
        if isinstance(first, dict):
            keys = list(first.keys())
            out: Block = {}
            for k in keys:
                vals = [r[k] for r in rows]
                out[k] = _stack(vals)
            return out
        return {"item": _stack(list(rows))}

    @staticmethod
    def to_rows(block: Block) -> List[Dict[str, Any]]:
        n = BlockAccessor.num_rows(block)
        keys = list(block.keys())
        return [{k: block[k][i] for k in keys} for i in range(n)]

    @staticmethod
    def from_pandas(df) -> Block:
        return {str(c): _as_array(df[c].to_numpy()) for c in df.columns}

    @staticmethod
    def to_pandas(block: Block):
        import pandas as pd

        return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                             for k, v in block.items()})

    @staticmethod
    def from_arrow(table) -> Block:
        out: Block = {}
        for name in table.column_names:
            col = table.column(name)
            try:
                out[name] = _as_array(col.to_numpy(zero_copy_only=False))
            except Exception:
                out[name] = np.array(col.to_pylist(), dtype=object)
        return out

    @staticmethod
    def validate(block: Block) -> Block:
        if not isinstance(block, dict):
            raise TypeError(
                f"a block must be a dict of numpy arrays, got {type(block)}"
                " — map_batches fns must return dict[str, array-like]")
        out = {k: _as_array(v) for k, v in block.items()}
        lengths = {k: len(v) for k, v in out.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"ragged block columns: {lengths}")
        return out


def _stack(vals: List[Any]) -> np.ndarray:
    first = np.asarray(vals[0])
    if first.dtype != object and first.ndim > 0:
        try:
            return np.stack([np.asarray(v) for v in vals])
        except ValueError:
            pass  # ragged → object column
    arr = np.empty(len(vals), dtype=object) if (
        first.dtype == object or first.ndim > 0) else None
    if arr is not None:
        for i, v in enumerate(vals):
            arr[i] = v
        return arr
    return np.asarray(vals)


class BlockMetadata:
    """Per-block bookkeeping carried alongside the ObjectRef
    (reference: data/block.py BlockMetadata)."""

    __slots__ = ("num_rows", "size_bytes")

    def __init__(self, num_rows: int, size_bytes: int):
        self.num_rows = num_rows
        self.size_bytes = size_bytes

    @staticmethod
    def of(block: Block) -> "BlockMetadata":
        return BlockMetadata(BlockAccessor.num_rows(block),
                             BlockAccessor.size_bytes(block))
