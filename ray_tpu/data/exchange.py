"""Push-based hash-shuffle exchange (reference: planner/exchange/ +
push_based_shuffle_task_scheduler.py:590, following the
Magnet/Exoshuffle line of work).

The pull-based two-stage exchange this replaces materialized every
partition fragment through the object plane (partition tasks with
``num_returns=n_out``, merge tasks pulling the parts afterwards).
Here map tasks PUSH each fragment to its owning reducer *as it is
produced*, over the cheapest transport the edge supports:

=============  =====================================================
transport      edge
=============  =====================================================
``shm``        mapper and reducer share a /dev/shm namespace and the
               native ring builds (experimental/channel.py, PR 1):
               one SPSC ring per (mapper process, reducer), frames
               assembled in slot memory — one memcpy end to end.
``dcn``        cross-host: the fragment rides the striped multi-
               stream push sockets (cluster/client.py
               ``broadcast_object``, PR 6) into the reducer node's
               plasma foreign cache; the accept RPC then resolves it
               locally.
``obj``        everything else (no native rings, single-process
               local mode fallbacks, transport errors): the fragment
               travels as a plain actor-call argument through the
               object plane.
=============  =====================================================

Reducers are streaming and spill-aware: raw-block exchanges buffer
fragments per output partition and move a partition's buffer to
plasma when it outgrows ``DataContext.shuffle_spill_limit_bytes``
(plasma LRU-spills to disk under its own pressure), while combinable
exchanges (groupby aggregates) fold every arriving fragment into a
running partial-state block and never hold raw rows at all.

Failure semantics: map tasks run with ``max_retries=0`` (a retried
map would re-push duplicate fragments); any map failure, reducer
error, or missed landing deadline tears down the reducers and rings
first and then raises a typed :class:`ShuffleError` — no hung reader
threads, no wedged reducers.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..exceptions import ChannelError, ShuffleError
from .block import Block, BlockAccessor
from .context import DataContext
from .executor import (OpStats, _meta, _RefGroup, _run_sample_wrapped)


def _shuffle_metrics():
    from ..observability.metrics import shuffle_counters

    return shuffle_counters()


def _host_key() -> str:
    """This process's /dev/shm namespace key — same convention as
    channel.channel_location: the node IP in cluster mode, "local"
    otherwise (all local-mode tasks/actors are threads in one
    process)."""
    from ..core.runtime import try_get_runtime

    rt = try_get_runtime()
    if rt is None or rt.cluster is None:
        return "local"
    return rt.address.rsplit(":", 1)[0]


# ---------------------------------------------------------------------------
# Reducer actor
# ---------------------------------------------------------------------------

class _ShuffleReducer:
    """Owns output partitions ``j`` with ``j % R == r``.  Sync +
    max_concurrency=1 (the channel-capability contract), so ring
    frames are drained by per-ring daemon reader threads instead of
    the actor mailbox — accept RPCs and ring pumps converge on
    :meth:`_ingest` under one lock."""

    def __init__(self, shuffle_id: str, merge_fn, combine, spec,
                 spill_limit: int, ring_timeout: float):
        self._sid = shuffle_id
        self._merge_fn = merge_fn
        self._combine = combine
        self._spec = spec
        self._spill_limit = int(spill_limit)
        self._ring_timeout = float(ring_timeout)
        self._lock = threading.Lock()
        # part_idx -> [(order_key, [block])]; deterministic replay
        # order is restored by sorting on order_key at take time.
        self._frags: Dict[int, List[Tuple[Any, List[Block]]]] = {}
        self._frag_bytes: Dict[int, int] = {}
        self._spilled: Dict[int, List[Any]] = {}  # part_idx -> [ref]
        self._states: Dict[int, Block] = {}       # combine mode
        self._received = 0
        self._queue_depth = 0
        self._error: Optional[str] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._readers: List[Any] = []
        self._ring_paths: List[str] = []

    def ping(self) -> str:
        return "ok"

    # -- transports ---------------------------------------------------------
    def attach_ring(self, path: str) -> None:
        """Register a mapper-created shm ring and pump it from a
        daemon thread (one ring per writing mapper process — SPSC)."""
        from ..experimental.channel import ChannelReader

        with self._lock:
            if path in self._ring_paths:
                return
            self._ring_paths.append(path)
        reader = ChannelReader(path, timeout=2.0)
        self._readers.append(reader)
        t = threading.Thread(target=self._ring_pump, args=(reader,),
                             daemon=True,
                             name=f"shfl-pump-{self._sid[:6]}")
        self._threads.append(t)
        t.start()

    def _ring_pump(self, reader) -> None:
        while not self._stop.is_set():
            try:
                frame = reader.get_value()
            except ChannelError as e:
                if self._stop.is_set():
                    return
                msg = str(e)
                # Short reader deadlines are the poll cadence, not a
                # failure: a slow mapper just hasn't pushed yet.
                if "deadline" in msg or "never created" in msg:
                    continue
                if ("torn down" in msg or "closed" in msg
                        or "destroyed" in msg):
                    return
                self._record_error(e)
                return
            except BaseException as e:  # noqa: BLE001 — reducer-side
                self._record_error(e)
                return
            try:
                sid, entries = frame
                if sid != self._sid:
                    continue
                for part_idx, order_key, piece in entries:
                    self._ingest(int(part_idx), order_key, [piece])
            except BaseException as e:  # noqa: BLE001
                self._record_error(e)
                return

    def accept(self, shuffle_id: str, entries) -> None:
        """Object-plane push: fragments arrive as call arguments."""
        if shuffle_id != self._sid:
            return
        for part_idx, order_key, piece in entries:
            self._ingest(int(part_idx), order_key, [piece])

    def accept_ref(self, shuffle_id: str, ref) -> None:
        """DCN push: ``broadcast_object`` landed the payload in this
        node's plasma foreign cache, so the get() resolves locally.
        Foreign-cache entries are EVICTABLE views — copy the arrays
        before buffering."""
        import ray_tpu

        if shuffle_id != self._sid:
            return
        for part_idx, order_key, piece in ray_tpu.get(ref):
            owned = {k: np.array(v, copy=True) for k, v in piece.items()}
            self._ingest(int(part_idx), order_key, [owned])

    # -- buffering / combining ----------------------------------------------
    def _ingest(self, part_idx: int, order_key, blocks: List[Block]
                ) -> None:
        if self._combine is not None:
            # Running partial aggregate: fold the fragment into the
            # partition's state block — raw rows are never retained.
            with self._lock:
                self._states[part_idx] = self._combine.add(
                    self._states.get(part_idx), blocks)
                self._received += 1
            return
        nbytes = sum(BlockAccessor.size_bytes(b) for b in blocks)
        spill: Optional[List[Tuple[Any, List[Block]]]] = None
        spill_bytes = 0
        with self._lock:
            self._frags.setdefault(part_idx, []).append(
                (order_key, blocks))
            self._queue_depth += 1
            self._received += 1
            total = self._frag_bytes.get(part_idx, 0) + nbytes
            if total >= self._spill_limit:
                # Partition outgrew its memory budget: hand the
                # buffered fragments to plasma (put happens OUTSIDE
                # the lock) and start a fresh buffer.
                spill = self._frags.pop(part_idx)
                spill_bytes, total = total, 0
            self._frag_bytes[part_idx] = total
            depth = self._queue_depth
        if spill is not None:
            import ray_tpu

            ref = ray_tpu.put(spill)
            with self._lock:
                self._spilled.setdefault(part_idx, []).append(ref)
                self._queue_depth -= len(spill)
                depth = self._queue_depth
            _shuffle_metrics()["spilled_bytes"].inc(spill_bytes)
        _shuffle_metrics()["reduce_queue_depth"].set(depth)

    def _record_error(self, err: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = f"{type(err).__name__}: {err}"

    # -- driver protocol ----------------------------------------------------
    def progress(self, shuffle_id: str) -> Dict[str, Any]:
        with self._lock:
            return {"received": self._received, "error": self._error}

    def take_partition(self, shuffle_id: str, part_idx: int):
        """Finalize one owned output partition: merge (or combine-
        finalize) everything that landed for it and return the blocks
        in the executor's ``(group, meta)`` convention."""
        import ray_tpu

        if self._combine is not None:
            with self._lock:
                state = self._states.pop(part_idx, None)
            blocks = self._combine.finalize(state, self._spec, part_idx)
        else:
            with self._lock:
                frags = self._frags.pop(part_idx, [])
                self._frag_bytes.pop(part_idx, None)
                refs = self._spilled.pop(part_idx, [])
                self._queue_depth -= len(frags)
                depth = self._queue_depth
            for ref in refs:
                frags.extend(ray_tpu.get(ref))
            # Fragments arrive in whatever order the transports race
            # them in; (map group, sequence) keys restore the exact
            # order the deleted pull path saw, keeping seeded
            # shuffles / stable sorts deterministic.
            frags.sort(key=lambda t: t[0])
            blocks = [b for _k, bl in frags for b in bl]
            blocks = self._merge_fn(blocks, self._spec, part_idx)
            _shuffle_metrics()["reduce_queue_depth"].set(depth)
        _shuffle_metrics()["partitions"].inc()
        return blocks, _meta(blocks)

    def shutdown(self) -> None:
        """Stop ring pumps, tear rings down, join threads."""
        from ..experimental.channel import destroy_channel

        self._stop.set()
        for path in list(self._ring_paths):
            try:
                destroy_channel(path)
            except Exception:
                pass
        for reader in self._readers:
            try:
                reader.close()
            except Exception:
                pass
        for t in self._threads:
            t.join(timeout=2.0)


# ---------------------------------------------------------------------------
# Map side
# ---------------------------------------------------------------------------

# Per-process ring registry: (shuffle_id, reducer_idx) -> (writer,
# lock).  Guarantees ONE writer endpoint per ring in this process
# (several map-task threads share it; ChannelWriter itself is not
# thread-safe), and bounds stale entries from finished exchanges.
_ring_registry: Dict[Tuple[str, int], Tuple[Any, threading.Lock]] = {}
_ring_registry_lock = threading.Lock()
_RING_REGISTRY_MAX = 64


def _evict_stale_rings() -> None:
    """Caller holds _ring_registry_lock."""
    while len(_ring_registry) > _RING_REGISTRY_MAX:
        _key, (writer, _l) = next(iter(_ring_registry.items()))
        _ring_registry.pop(_key)
        try:
            writer.destroy()
        except Exception:
            pass


class _FragmentSender:
    """Per-map-task transport mux: picks shm ring / dcn push / object
    plane per reducer and counts ``ray_tpu_shuffle_bytes`` by
    transport on the send side."""

    def __init__(self, sid: str, infos, ring_slots: int,
                 timeout: float):
        self._sid = sid
        self._infos = infos  # [(handle, host_key, node_address)]
        self._ring_slots = ring_slots
        self._timeout = timeout
        self._host = _host_key()
        self._no_ring: set = set()
        self._pinned_refs: List[Any] = []

    def _ring_for(self, r: int):
        """The (writer, lock) shm endpoint for reducer ``r``, created
        (and announced via attach_ring) once per process, or None when
        the edge can't ride a ring."""
        from ..experimental.channel import (ChannelWriter, channel_path,
                                            channels_available)

        if r in self._no_ring:
            return None
        handle, host, _addr = self._infos[r]
        if host is None or host != self._host:
            return None
        if not channels_available():
            return None
        key = (self._sid, r)
        with _ring_registry_lock:
            ent = _ring_registry.get(key)
            if ent is not None:
                return ent
        # Create outside the registry lock (attach is a remote call);
        # losing a creation race just means one redundant ring.
        import ray_tpu

        path = channel_path(f"shfl-{self._sid[:6]}-r{r}")
        writer = ChannelWriter(path, n_slots=self._ring_slots,
                               timeout=self._timeout)
        try:
            ray_tpu.get(handle.attach_ring.remote(path))
        except Exception:
            self._no_ring.add(r)
            return None
        with _ring_registry_lock:
            ent = _ring_registry.get(key)
            if ent is None:
                ent = _ring_registry[key] = (writer, threading.Lock())
                _evict_stale_rings()
        return ent

    def flush(self, r: int, entries, pending: List[Any]) -> int:
        """Push one coalesced fragment list to reducer ``r``.  Returns
        the number of fragment entries delivered (the driver's
        progress accounting unit)."""
        from ray_tpu.experimental.chaos import ChaosKill

        handle, _host, addr = self._infos[r]
        nbytes = sum(BlockAccessor.size_bytes(p) for _i, _k, p in entries)
        ent = self._ring_for(r)
        if ent is not None:
            writer, lock = ent
            try:
                with lock:
                    writer.put_value((self._sid, entries))
                _shuffle_metrics()["bytes"].inc(
                    nbytes, tags={"transport": "shm"})
                return len(entries)
            except ChaosKill:
                raise
            except Exception:
                # Ring failed mid-exchange (torn down, oversized ring
                # create, native error): degrade this reducer edge to
                # the object plane for the rest of the task.
                self._no_ring.add(r)
        if addr is not None and self._host not in (None, "local") \
                and addr.rsplit(":", 1)[0] != self._host:
            # Cross-host: pre-push the payload over the striped DCN
            # sockets so the reducer's get() resolves from its local
            # foreign cache instead of pulling back across hosts.
            from ..core.runtime import try_get_runtime

            rt = try_get_runtime()
            if rt is not None and rt.cluster is not None:
                import ray_tpu

                try:
                    ref = ray_tpu.put(entries)
                    self._pinned_refs.append(ref)
                    rt.cluster.broadcast_object(
                        ref, [addr], timeout=self._timeout)
                    pending.append(
                        handle.accept_ref.remote(self._sid, ref))
                    _shuffle_metrics()["bytes"].inc(
                        nbytes, tags={"transport": "dcn"})
                    return len(entries)
                except Exception:
                    pass  # fall through to the object plane
        pending.append(handle.accept.remote(self._sid, entries))
        _shuffle_metrics()["bytes"].inc(nbytes, tags={"transport": "obj"})
        return len(entries)


def _push_map_task(group, sid: str, partition_fn, n_out: int, spec,
                   offset: int, group_idx: int, infos,
                   frag_bytes: int, ring_slots: int,
                   timeout: float) -> List[int]:
    """One map task: partition this input group's blocks and push
    every fragment to its owning reducer as produced, coalescing per
    reducer up to ``frag_bytes``.  Returns per-reducer entry counts —
    the driver's expected-landing ledger.  MUST run with
    max_retries=0: a retry would push duplicates."""
    import ray_tpu

    blocks = group.resolve() if isinstance(group, _RefGroup) else group
    R = len(infos)
    sender = _FragmentSender(sid, infos, ring_slots, timeout)
    bufs: List[List[Tuple[int, Tuple[int, int], Block]]] = \
        [[] for _ in range(R)]
    buf_bytes = [0] * R
    counts = [0] * R
    pending: List[Any] = []
    seq = 0
    off = int(offset)
    for block in blocks:
        for idx, piece in partition_fn(block, n_out, spec, off):
            if not BlockAccessor.num_rows(piece):
                continue
            r = idx % R
            bufs[r].append((idx, (group_idx, seq), piece))
            seq += 1
            buf_bytes[r] += BlockAccessor.size_bytes(piece)
            if buf_bytes[r] >= frag_bytes:
                counts[r] += sender.flush(r, bufs[r], pending)
                bufs[r], buf_bytes[r] = [], 0
        off += BlockAccessor.num_rows(block)
    for r in range(R):
        if bufs[r]:
            counts[r] += sender.flush(r, bufs[r], pending)
    # Await the accept RPCs: the task ends only once its object-plane
    # and DCN fragments are INSIDE the reducers (pins the payload refs
    # until delivery, and makes the returned counts a lower bound the
    # driver can trust).
    if pending:
        ray_tpu.get(pending)
    return counts


# ---------------------------------------------------------------------------
# Driver orchestration
# ---------------------------------------------------------------------------

def exchange_streaming(source, op, ctx: Optional[DataContext], stats):
    """Run one Exchange op push-based.  Yields one ``(group, meta)``
    ref per output partition, in partition order — the same contract
    as every other streaming phase."""
    import ray_tpu

    ctx = ctx or DataContext.get_current()
    op_stats = OpStats(op.name)
    if stats is not None:
        stats.ops.append(op_stats)
    t0 = time.perf_counter()
    input_refs = list(source)
    if not input_refs:
        op_stats.wall_s = time.perf_counter() - t0
        return iter(())

    n_out = op.n_out if op.n_out > 0 else len(input_refs)
    if op.needs_offsets:
        # Sample stage: group row counts (exact global offsets) plus
        # the op's own samples (e.g. sort range bounds).
        remote_sample = ray_tpu.remote(_run_sample_wrapped)
        sampled = ray_tpu.get(
            [remote_sample.remote(_RefGroup(r), op.sample_fn)
             for r in input_refs])
        rows_per_group = [s[0] for s in sampled]
        offsets = list(np.cumsum([0] + rows_per_group[:-1]))
        spec = None
        if op.sample_fn is not None:
            spec = op.bounds_fn([s[1] for s in sampled], n_out)
        if op.n_out <= 0 and sum(rows_per_group) == 0:
            op_stats.wall_s = time.perf_counter() - t0
            return iter(())
        spec = {"spec": spec, "total": int(sum(rows_per_group))}
    else:
        # The "offset" handed to the partition fn is the group INDEX —
        # enough to decorrelate per-group randomness under a fixed
        # seed.
        offsets = list(range(len(input_refs)))
        spec = {"spec": None, "total": -1}

    sid = uuid.uuid4().hex[:12]
    R = max(1, min(n_out, ctx.shuffle_reducers))
    Reducer = ray_tpu.remote(_ShuffleReducer)
    reducers = [
        Reducer.remote(sid, op.merge_fn, op.combine, spec,
                       ctx.shuffle_spill_limit_bytes,
                       ctx.shuffle_timeout_s)
        for _ in range(R)]

    def teardown():
        for h in reducers:
            try:
                ray_tpu.wait([h.shutdown.remote()], num_returns=1,
                             timeout=5.0)
            except Exception:
                pass
            try:
                ray_tpu.kill(h)
            except Exception:
                pass

    def abort(reason: str, cause: Optional[BaseException] = None,
              extra: Optional[dict] = None):
        # The enclosing except tears the reducers/rings down before
        # this propagates out of the exchange.
        err = ShuffleError(reason, context={
            "exchange": op.name, "shuffle_id": sid, **(extra or {})})
        if cause is not None:
            raise err from cause
        raise err

    try:
        # Reducers must be ALIVE before the channel-capability probe,
        # or every same-host edge would silently degrade to obj.
        ray_tpu.get([h.ping.remote() for h in reducers])
        from ..experimental.channel import (channel_location,
                                            channels_available)

        infos = []
        for h in reducers:
            loc = channel_location(h) if channels_available() else None
            infos.append((h, loc[0] if loc else None,
                          loc[1] if loc else None))

        remote_map = ray_tpu.remote(_push_map_task).options(
            max_retries=0)
        map_refs = [
            remote_map.remote(
                _RefGroup(ref), sid, op.partition_fn, n_out, spec,
                int(off), i, infos, ctx.shuffle_fragment_bytes,
                ctx.shuffle_ring_slots, ctx.shuffle_timeout_s)
            for i, (ref, off) in enumerate(zip(input_refs, offsets))]
        op_stats.num_tasks += len(map_refs)

        expected = [0] * R
        pending_maps = list(map_refs)
        while pending_maps:
            ready, pending_maps = ray_tpu.wait(
                pending_maps, num_returns=1, timeout=None)
            for ref in ready:
                try:
                    counts = ray_tpu.get(ref)
                except BaseException as e:  # noqa: BLE001
                    abort("map task failed mid-shuffle", cause=e)
                for r, c in enumerate(counts):
                    expected[r] += c

        # All map tasks returned: their obj/dcn fragments are already
        # inside the reducers; ring frames may still be in flight —
        # poll the reducers' landing ledgers up to the deadline.
        deadline = time.monotonic() + ctx.shuffle_timeout_s
        pause = threading.Event()  # never set: wait() = bounded sleep
        while True:
            prog = ray_tpu.get(
                [h.progress.remote(sid) for h in reducers])
            errs = [p["error"] for p in prog if p["error"]]
            if errs:
                abort("reducer failed mid-shuffle",
                      extra={"reducer_error": errs[0]})
            if all(p["received"] >= e
                   for p, e in zip(prog, expected)):
                break
            if time.monotonic() > deadline:
                abort("pushed fragments never landed within "
                      f"{ctx.shuffle_timeout_s:g}s",
                      extra={"expected": expected,
                             "received": [p["received"] for p in prog]})
            pause.wait(timeout=0.02)

        out_refs = [reducers[j % R].take_partition.remote(sid, j)
                    for j in range(n_out)]
        op_stats.num_tasks += n_out
    except BaseException:
        teardown()
        raise

    def gen():
        try:
            for ref in out_refs:
                ray_tpu.wait([ref], num_returns=1, timeout=None)
                op_stats.num_blocks += 1
                yield ref
        finally:
            op_stats.wall_s = time.perf_counter() - t0
            teardown()

    return gen()
