"""Read tasks and datasources.

Reference: python/ray/data/read_api.py:335 (``read_datasource``) plans a
``Read`` logical op whose physical form is a set of ``ReadTask`` closures,
each producing one or more blocks when executed remotely
(data/datasource/datasource.py).  Same shape here: a ``Datasource`` yields
picklable zero-arg ``ReadTask``s; the streaming executor runs them as
``ray_tpu`` tasks exactly like any other map stage.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from .block import Block, BlockAccessor

# A ReadTask is a zero-arg callable returning a list of blocks.
ReadTask = Callable[[], List[Block]]


class Datasource:
    def read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimated_num_rows(self) -> Optional[int]:
        return None


class RangeDatasource(Datasource):
    """``ray_tpu.data.range`` (reference: read_api.py range/range_tensor)."""

    def __init__(self, n: int, column: str = "id"):
        self.n = n
        self.column = column

    def estimated_num_rows(self):
        return self.n

    def read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        bounds = np.linspace(0, self.n, parallelism + 1).astype(np.int64)
        col = self.column

        def make(lo: int, hi: int) -> ReadTask:
            return lambda: [{col: np.arange(lo, hi, dtype=np.int64)}]

        return [make(int(lo), int(hi))
                for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo]


class ItemsDatasource(Datasource):
    """``from_items`` — rows already in driver memory."""

    def __init__(self, items: Sequence[Any]):
        self.items = list(items)

    def estimated_num_rows(self):
        return len(self.items)

    def read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self.items)
        parallelism = max(1, min(parallelism, n or 1))
        bounds = np.linspace(0, n, parallelism + 1).astype(np.int64)
        tasks: List[ReadTask] = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            if hi <= lo:
                continue
            chunk = self.items[int(lo):int(hi)]
            tasks.append(
                lambda chunk=chunk: [BlockAccessor.from_rows(chunk)])
        return tasks


class BlocksDatasource(Datasource):
    """Wrap pre-built blocks (from_numpy / from_pandas / from_arrow)."""

    def __init__(self, blocks: List[Block]):
        self.blocks = [BlockAccessor.validate(b) for b in blocks]

    def estimated_num_rows(self):
        return sum(BlockAccessor.num_rows(b) for b in self.blocks)

    def read_tasks(self, parallelism: int) -> List[ReadTask]:
        return [lambda b=b: [b] for b in self.blocks]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


class FileDatasource(Datasource):
    """One read task per file (reference: file_based_datasource.py)."""

    def __init__(self, paths, reader: Callable[[str], List[Block]]):
        self.paths = _expand_paths(paths)
        self.reader = reader

    def read_tasks(self, parallelism: int) -> List[ReadTask]:
        reader = self.reader
        return [lambda p=p: reader(p) for p in self.paths]


def _read_parquet_file(path: str, columns=None) -> List[Block]:
    import pyarrow as pa
    import pyarrow.parquet as pq

    # Plain Python file read + BufferReader, NOT pq.read_table(path):
    # both the ParquetDataset machinery and arrow's LocalFileSystem
    # open_input_file segfault when first exercised from a worker
    # thread in a process with many native libs loaded (observed
    # reproducibly under the full test suite; fine in isolation).
    # Reading bytes ourselves keeps arrow's filesystem layer out of
    # worker threads entirely.
    with open(path, "rb") as f:
        buf = f.read()
    table = pq.ParquetFile(pa.BufferReader(buf)).read(
        columns=columns, use_threads=False)
    return [BlockAccessor.from_arrow(table)]


def _read_csv_file(path: str, **kw) -> List[Block]:
    import pandas as pd

    return [BlockAccessor.from_pandas(pd.read_csv(path, **kw))]


def _read_json_file(path: str) -> List[Block]:
    import json

    rows = []
    with open(path) as f:
        text = f.read().strip()
    if text.startswith("["):
        rows = json.loads(text)
    else:  # jsonl
        rows = [json.loads(line) for line in text.splitlines() if line]
    return [BlockAccessor.from_rows(rows)]


def _read_numpy_file(path: str) -> List[Block]:
    arr = np.load(path, allow_pickle=False)
    if isinstance(arr, np.lib.npyio.NpzFile):
        return [{k: arr[k] for k in arr.files}]
    return [{"data": arr}]


def _read_image_file(path: str, *, size=None, mode=None) -> List[Block]:
    """Decode one image into {"image": HWC uint8 array, "path": str}
    (reference: datasource/image_datasource.py)."""
    from PIL import Image

    img = Image.open(path)
    if mode:
        img = img.convert(mode)
    if size:
        img = img.resize(tuple(size))
    return [{"image": np.asarray(img)[None, ...],
             "path": np.asarray([path])}]


def image_datasource(paths, *, size=None, mode=None) -> FileDatasource:
    return FileDatasource(
        paths, lambda p: _read_image_file(p, size=size, mode=mode))


def tfrecords_datasource(paths) -> FileDatasource:
    from .tfrecords import read_tfrecords_file

    return FileDatasource(paths, read_tfrecords_file)


def parquet_datasource(paths, columns=None) -> FileDatasource:
    return FileDatasource(
        paths, lambda p: _read_parquet_file(p, columns=columns))


def csv_datasource(paths, **kw) -> FileDatasource:
    return FileDatasource(paths, lambda p: _read_csv_file(p, **kw))


def json_datasource(paths) -> FileDatasource:
    return FileDatasource(paths, _read_json_file)


def numpy_datasource(paths) -> FileDatasource:
    return FileDatasource(paths, _read_numpy_file)
