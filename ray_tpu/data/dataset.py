"""Dataset facade: lazy logical plan + consumption APIs.

Reference: python/ray/data/dataset.py:146 (``Dataset`` — lazy plan,
``iter_batches`` :3935, ``materialize`` :4897) and
``streaming_split`` → output_splitter (used by
train/_internal/data_config.py for per-worker shards).

TPU-first notes: batches are dict[str, np.ndarray] — exactly what a jit
train step takes; ``iter_batches(device_put=True)`` overlaps host→HBM
transfer of batch N+1 with the consumer's step N (the reference's
prefetching batcher + GPU pinning, block_batching/).
"""

from __future__ import annotations

import builtins
import os

import itertools
import threading
from collections import deque
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Union)

import numpy as np

from .block import (Block, BlockAccessor, BlockMetadata,
                    group_boundaries, hash_partition_indices,
                    sort_by_key)
from .context import DataContext
from .datasource import (BlocksDatasource, Datasource, ItemsDatasource,
                         RangeDatasource, csv_datasource, json_datasource,
                         numpy_datasource, parquet_datasource)
from .executor import (ActorMapBlocks, ActorPoolStrategy, AllToAll,
                       Exchange, Limit, LogicalOp, MapBlocks, PlanStats,
                       Read, UnionOp, ZipOp, execute_streaming)


class Dataset:
    """Lazy, immutable pipeline of blocks.  Every transform returns a new
    Dataset sharing the prefix of the plan (reference dataset.py:146)."""

    def __init__(self, ops: List[LogicalOp]):
        self._ops = ops
        self._last_stats: Optional[PlanStats] = None

    # -- transforms ---------------------------------------------------------
    def _with(self, op: LogicalOp) -> "Dataset":
        return Dataset(self._ops + [op])

    def map_batches(self, fn, *,
                    batch_size: Optional[int] = None,
                    compute: Optional[ActorPoolStrategy] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None
                    ) -> "Dataset":
        """Apply ``fn`` to batches (reference dataset.map_batches).
        With ``batch_size=None`` the fn sees whole blocks (zero-copy);
        otherwise blocks are re-chunked to exactly ``batch_size`` rows
        inside the task.

        ``compute=ActorPoolStrategy(size=n)`` makes this a stateful
        actor-pool stage (reference actor_pool_map_operator.py:34):
        ``fn`` must be a CLASS, instantiated once per pool actor with
        ``fn_constructor_args``; each batch goes through
        ``instance(batch)``."""
        if compute is not None:
            if not callable(fn) or not isinstance(fn, type):
                raise TypeError(
                    "compute=ActorPoolStrategy requires fn to be a "
                    "class (instantiated once per pool actor)")
            return self._with(ActorMapBlocks(
                fn.__name__, fn, tuple(fn_constructor_args),
                dict(fn_constructor_kwargs or {}), batch_size, compute))
        if batch_size is None:
            def tf(block: Block) -> List[Block]:
                return [BlockAccessor.validate(fn(block))]
        else:
            def tf(block: Block) -> List[Block]:
                out = []
                n = BlockAccessor.num_rows(block)
                for lo in builtins.range(0, n, batch_size):
                    piece = BlockAccessor.slice(block, lo,
                                                min(lo + batch_size, n))
                    out.append(BlockAccessor.validate(fn(piece)))
                return out
        return self._with(MapBlocks("MapBatches", tf))

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]
            ) -> "Dataset":
        def tf(block: Block) -> List[Block]:
            rows = [fn(r) for r in BlockAccessor.to_rows(block)]
            return [BlockAccessor.from_rows(rows)]
        return self._with(MapBlocks("Map", tf))

    def flat_map(self, fn: Callable[[Dict[str, Any]], Sequence[Dict]]
                 ) -> "Dataset":
        def tf(block: Block) -> List[Block]:
            rows: List[Dict[str, Any]] = []
            for r in BlockAccessor.to_rows(block):
                rows.extend(fn(r))
            return [BlockAccessor.from_rows(rows)] if rows else []
        return self._with(MapBlocks("FlatMap", tf))

    def filter(self, fn: Callable[[Dict[str, Any]], bool]) -> "Dataset":
        def tf(block: Block) -> List[Block]:
            keep = np.fromiter(
                (bool(fn(r)) for r in BlockAccessor.to_rows(block)),
                dtype=bool, count=BlockAccessor.num_rows(block))
            return [BlockAccessor.take(block, np.nonzero(keep)[0])]
        return self._with(MapBlocks("Filter", tf))

    def limit(self, n: int) -> "Dataset":
        return self._with(Limit(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        """Distributed exchange: each input splits into ``num_blocks``
        row ranges (partition tasks), one merge task concatenates each
        range (reference: planner/exchange/ — no block values cross the
        driver)."""
        def partition(block: Block, n: int, spec, offset: int):
            # Exact global row ranges from the sampled total: output
            # partition j covers global rows [bounds[j], bounds[j+1]).
            total = spec["total"]
            bounds = np.linspace(0, total, n + 1).astype(np.int64)
            rows = BlockAccessor.num_rows(block)
            out = []
            for j in builtins.range(n):
                lo = max(int(bounds[j]) - offset, 0)
                hi = min(int(bounds[j + 1]) - offset, rows)
                if hi > lo:
                    out.append((j, BlockAccessor.slice(block, lo, hi)))
            return out

        def merge(blocks: List[Block], _spec, _idx) -> List[Block]:
            return [BlockAccessor.concat(blocks)] if blocks else []

        return self._with(Exchange("Repartition", partition, merge,
                                   n_out=num_blocks,
                                   needs_offsets=True))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Distributed shuffle (reference: push-based shuffle,
        push_based_shuffle_task_scheduler.py:590): partition tasks deal
        rows to random output partitions; each merge task concatenates
        its parts and permutes locally.  Values move node-to-node."""
        def partition(block: Block, n: int, _spec, offset: int):
            rows = BlockAccessor.num_rows(block)
            # Fold the global offset into the stream so blocks don't
            # share one assignment pattern under a fixed seed.
            rng = np.random.default_rng(
                None if seed is None else (seed, offset))
            assign = rng.integers(0, n, rows)
            return [(j, BlockAccessor.take(block,
                                           np.nonzero(assign == j)[0]))
                    for j in builtins.range(n)]

        def merge(blocks: List[Block], _spec, part_idx) -> List[Block]:
            if not blocks:
                return []
            whole = BlockAccessor.concat(blocks)
            # Fold the merge partition index into the seed so output
            # partitions don't share one permutation pattern.
            rng = np.random.default_rng(
                None if seed is None else (seed, part_idx))
            perm = rng.permutation(BlockAccessor.num_rows(whole))
            return [BlockAccessor.take(whole, perm)]

        return self._with(Exchange("RandomShuffle", partition, merge))

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        """Distributed range sort (reference SortTaskSpec,
        sort_task_spec.py:94): sample tasks pick range bounds, partition
        tasks split by range, merge tasks sort each range locally; the
        ordered ranges concatenate into the global order."""
        def sample(blocks: List[Block]):
            vals = np.concatenate([np.asarray(b[key]) for b in blocks]) \
                if blocks else np.asarray([])
            if len(vals) > 100:
                idx = np.linspace(0, len(vals) - 1, 100).astype(np.int64)
                vals = np.sort(vals)[idx]
            return vals

        def bounds(samples, n: int):
            allv = np.sort(np.concatenate(
                [np.asarray(s) for s in samples if len(s)]))
            if len(allv) == 0:
                return np.asarray([])
            qs = np.linspace(0, len(allv) - 1, n + 1).astype(np.int64)
            return allv[qs[1:-1]]

        def partition(block: Block, n: int, spec, _offset: int):
            spec = spec["spec"]
            vals = np.asarray(block[key])
            idx = np.searchsorted(spec, vals, side="right") \
                if len(spec) else np.zeros(len(vals), np.int64)
            if descending:
                idx = (n - 1) - idx
            return [(j, BlockAccessor.take(block,
                                           np.nonzero(idx == j)[0]))
                    for j in builtins.range(n)]

        def merge(blocks: List[Block], _spec, _idx) -> List[Block]:
            if not blocks:
                return []
            whole = BlockAccessor.concat(blocks)
            order = np.argsort(np.asarray(whole[key]), kind="stable")
            if descending:
                order = order[::-1]
            return [BlockAccessor.take(whole, order)]

        return self._with(Exchange("Sort", partition, merge,
                                   sample_fn=sample, bounds_fn=bounds))

    # -- relational ops (push exchange) --------------------------------------
    def groupby(self, key: str) -> "GroupedData":
        """Hash-partition rows by ``key`` for aggregation (reference:
        Dataset.groupby → GroupedData).  All NaN keys form one group;
        output groups are key-sorted within each output partition but
        partitions are in hash order, not key order."""
        return GroupedData(self, key)

    def aggregate(self, *aggs) -> Optional[Dict[str, Any]]:
        """Whole-dataset aggregation (reference: Dataset.aggregate):
        ``ds.aggregate(Sum("x"), ("mean", "y"), "count")`` → one dict
        of results, or None on an empty dataset."""
        from .aggregate import resolve_aggregate

        resolved = [resolve_aggregate(a) for a in aggs]
        if not resolved:
            raise ValueError("aggregate() needs at least one aggregate")
        rows = _aggregate_exchange(self, None, resolved).take_all()
        if not rows:
            return None
        return dict(rows[0])

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-concatenate with ``other``, position-aligned
        (reference: Dataset.zip).  Row counts must match —
        :class:`~ray_tpu.exceptions.ZipLengthMismatchError` otherwise;
        colliding column names from ``other`` get a ``_1`` suffix."""
        return self._with(ZipOp(list(other._ops)))

    def union(self, *others: "Dataset") -> "Dataset":
        """Append the other datasets' blocks after this one's
        (reference: Dataset.union).  Column sets must agree —
        :class:`~ray_tpu.exceptions.UnionSchemaError` otherwise."""
        if not others:
            return self
        return self._with(UnionOp([list(o._ops) for o in others]))

    # -- execution ----------------------------------------------------------
    def iter_blocks(self) -> Iterator[Block]:
        self._last_stats = PlanStats()
        return execute_streaming(self._ops, stats=self._last_stats)

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False,
                     batch_format: str = "numpy",
                     prefetch_batches: Optional[int] = None,
                     device_put: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None
                     ) -> Iterator[Any]:
        """Stream exact-size batches (reference dataset.py:3935 +
        _internal/batcher.py).  ``device_put=True`` moves each batch to
        the default jax device one batch ahead of the consumer.
        ``local_shuffle_buffer_size`` permutes rows through a rolling
        buffer of at least that many rows before batching — the cheap
        within-shard decorrelation Train ingestion uses between full
        shuffled epochs (a ``random_shuffle()`` exchange)."""
        ctx = DataContext.get_current()
        depth = (ctx.prefetch_batches if prefetch_batches is None
                 else prefetch_batches)
        return _assemble_batches(
            self.iter_blocks(), batch_size=batch_size,
            drop_last=drop_last, batch_format=batch_format,
            prefetch=depth, device_put=device_put,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self.iter_blocks():
            yield from BlockAccessor.to_rows(block)

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        return list(itertools.islice(self.limit(n).iter_rows(), n))

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(BlockAccessor.num_rows(b) for b in self.iter_blocks())

    def schema(self) -> Optional[Dict[str, np.dtype]]:
        for block in self.iter_blocks():
            return BlockAccessor.schema(block)
        return None

    def materialize(self) -> "Dataset":
        """Execute now; the result re-reads from memory
        (reference dataset.py:4897)."""
        blocks = list(self.iter_blocks())
        return Dataset([Read(BlocksDatasource(blocks))])

    def stats(self) -> str:
        if self._last_stats is None:
            return "(dataset not executed yet)"
        return self._last_stats.summary()

    # -- writers (reference: Dataset.write_* → one file per block) ----------
    def _write_files(self, path: str, ext: str, write_block) -> List[str]:
        os.makedirs(path, exist_ok=True)
        out = []
        for i, block in enumerate(self.iter_blocks()):
            fp = os.path.join(path, f"{i:06d}.{ext}")
            write_block(fp, block)
            out.append(fp)
        return out

    def write_parquet(self, path: str) -> List[str]:
        import pyarrow as pa
        import pyarrow.parquet as pq

        def w(fp, block):
            pq.write_table(pa.table(
                {k: np.asarray(v) for k, v in block.items()}), fp)

        return self._write_files(path, "parquet", w)

    def write_csv(self, path: str) -> List[str]:
        def w(fp, block):
            BlockAccessor.to_pandas(block).to_csv(fp, index=False)

        return self._write_files(path, "csv", w)

    def write_json(self, path: str) -> List[str]:
        import json

        def w(fp, block):
            with open(fp, "w") as f:
                for row in BlockAccessor.to_rows(block):
                    f.write(json.dumps(
                        {k: (v.tolist() if hasattr(v, "tolist") else v)
                         for k, v in row.items()}) + "\n")

        return self._write_files(path, "jsonl", w)

    def write_tfrecords(self, path: str) -> List[str]:
        from .tfrecords import write_tfrecords_file

        def w(fp, block):
            write_tfrecords_file(fp, [block])

        return self._write_files(path, "tfrecords", w)

    def to_random_access_dataset(self, key: str, *,
                                 num_workers: int = 2):
        """Keyed O(log n) lookup structure over the sorted dataset
        (reference: Dataset.to_random_access_dataset)."""
        from .random_access import RandomAccessDataset

        return RandomAccessDataset(self, key, num_workers=num_workers)

    # -- splitting (Train integration) --------------------------------------
    def streaming_split(self, n: int, *, equal: bool = True
                        ) -> List["DataIterator"]:
        """N per-consumer iterators over ONE shared execution
        (reference: Dataset.streaming_split → output_splitter op, the
        API train/_internal/data_config.py shards datasets with).
        ``equal=True`` slices every block into n row-balanced pieces
        (shards stay within ±1 row of each other, keeping a lockstep
        training gang in sync); ``equal=False`` deals whole blocks
        round-robin.  Consumers advance epochs in lockstep.
        """
        router = _SplitRouter(self, n, equal=equal)
        return [DataIterator(router, i) for i in builtins.range(n)]

    def split(self, n: int) -> List["Dataset"]:
        """Materializing split into n row-balanced datasets."""
        blocks = list(self.iter_blocks())
        whole = BlockAccessor.concat(blocks)
        rows = BlockAccessor.num_rows(whole)
        bounds = np.linspace(0, rows, n + 1).astype(np.int64)
        return [Dataset([Read(BlocksDatasource(
            [BlockAccessor.slice(whole, int(lo), int(hi))]))])
                for lo, hi in zip(bounds[:-1], bounds[1:])]

    def __repr__(self):
        names = [getattr(op, "name", type(op).__name__)
                 for op in self._ops]
        return f"Dataset({' -> '.join(names)})"


class GroupedData:
    """Deferred groupby (reference: grouped_data.py GroupedData): the
    aggregate/map_groups call appends the push-exchange op to the
    plan.  Aggregations combine INCREMENTALLY on the reducers (partial
    state per distinct key, never raw rows); ``map_groups`` ships raw
    rows and applies the fn per key-run after the shuffle."""

    def __init__(self, ds: Dataset, key: str):
        if not isinstance(key, str):
            raise TypeError(
                f"groupby key must be a column name, got {key!r}")
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs) -> Dataset:
        from .aggregate import resolve_aggregate

        resolved = [resolve_aggregate(a) for a in aggs]
        if not resolved:
            raise ValueError("aggregate() needs at least one aggregate")
        return _aggregate_exchange(self._ds, self._key, resolved)

    def count(self) -> Dataset:
        from .aggregate import Count

        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        from .aggregate import Sum

        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        from .aggregate import Min

        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        from .aggregate import Max

        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        from .aggregate import Mean

        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 0) -> Dataset:
        from .aggregate import Std

        return self.aggregate(Std(on, ddof=ddof))

    def map_groups(self, fn: Callable[[Block], Any]) -> Dataset:
        """Apply ``fn`` to each whole group (a Block of that key's
        rows); it returns a Block of any shape (reference:
        GroupedData.map_groups)."""
        key = self._key

        def partition(block: Block, n: int, _spec, _offset: int):
            idx = hash_partition_indices(block, key, n)
            return [(j, BlockAccessor.take(block,
                                           np.nonzero(idx == j)[0]))
                    for j in builtins.range(n)]

        def merge(blocks: List[Block], _spec, _idx) -> List[Block]:
            if not blocks:
                return []
            sb = sort_by_key(BlockAccessor.concat(blocks), key)
            bounds = group_boundaries(sb[key])
            outs: List[Block] = []
            for s, e in zip(bounds[:-1], bounds[1:]):
                res = BlockAccessor.validate(
                    fn(BlockAccessor.slice(sb, int(s), int(e))))
                if BlockAccessor.num_rows(res):
                    outs.append(res)
            return outs

        return self._ds._with(
            Exchange(f"MapGroups({key})", partition, merge))


def _aggregate_exchange(ds: Dataset, key: Optional[str],
                        aggs) -> Dataset:
    from .aggregate import AggCombine, make_agg_partition

    return ds._with(Exchange(
        f"GroupBy({key})" if key is not None else "Aggregate",
        make_agg_partition(key, aggs), None,
        n_out=1 if key is None else -1,
        combine=AggCombine(key, aggs)))


class _SplitRouter:
    """Routes blocks of one shared streaming execution to n consumers,
    round-robin by block index.  Epoch-aware: a consumer that finishes
    epoch e and starts epoch e+1 blocks until every consumer has
    finished epoch e, then the plan re-executes (reference
    DataIterators are re-iterable; training loops advance epochs in
    lockstep)."""

    _END = object()

    def __init__(self, ds: Dataset, n: int, equal: bool = True):
        self._n = n
        self._equal = equal
        self._cond = threading.Condition()
        self._queues: List[deque] = [deque() for _ in builtins.range(n)]
        self._source: Optional[Iterator[Block]] = None
        self._ds = ds
        self._next = 0
        self._done = False
        self._finished: set = set()
        self._epoch = 0

    def _deal(self, block: Block):
        if not self._equal:
            self._queues[self._next].append(block)
            self._next = (self._next + 1) % self._n
            return
        # Row-balanced: slice the block into n contiguous pieces,
        # rotating which shard gets the (possibly longer) first piece
        # so remainders even out across blocks.
        rows = BlockAccessor.num_rows(block)
        bounds = np.linspace(0, rows, self._n + 1).astype(np.int64)
        for j in builtins.range(self._n):
            lo, hi = int(bounds[j]), int(bounds[j + 1])
            if hi > lo:
                shard = (j + self._next) % self._n
                self._queues[shard].append(
                    BlockAccessor.slice(block, lo, hi))
        self._next = (self._next + 1) % self._n

    def next_block(self, shard: int, epoch: int) -> Any:
        """Next block for ``shard`` in ``epoch``, or ``_END`` at the end
        of that shard's epoch."""
        with self._cond:
            while epoch > self._epoch:
                # This consumer is ahead; wait for laggards to finish
                # the current epoch.
                self._cond.wait(timeout=1.0)
            if epoch < self._epoch:
                # The epoch this iterator belongs to is over.
                return self._END
            while not self._queues[shard]:
                if self._done:
                    if shard not in self._finished:
                        self._finished.add(shard)
                        if len(self._finished) == self._n:
                            # Everyone finished: rearm for next epoch.
                            self._source = None
                            self._done = False
                            self._finished = set()
                            self._next = 0
                            self._epoch += 1
                            self._cond.notify_all()
                    return self._END
                if self._source is None:
                    self._source = self._ds.iter_blocks()
                try:
                    block = next(self._source)
                except StopIteration:
                    self._done = True
                    continue
                self._deal(block)
                self._cond.notify_all()
            return self._queues[shard].popleft()


class DataIterator:
    """Per-worker view of a streaming_split (reference:
    data/iterator.py DataIterator)."""

    def __init__(self, router: _SplitRouter, shard: int):
        self._router = router
        self._shard = shard
        self._epoch = 0

    def iter_blocks(self) -> Iterator[Block]:
        epoch = self._epoch
        self._epoch += 1
        while True:
            block = self._router.next_block(self._shard, epoch)
            if block is _SplitRouter._END:
                return
            yield block

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     device_put: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None
                     ) -> Iterator[Any]:
        return _assemble_batches(
            self.iter_blocks(), batch_size=batch_size,
            drop_last=drop_last, batch_format=batch_format,
            prefetch=prefetch_batches, device_put=device_put,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self.iter_blocks():
            yield from BlockAccessor.to_rows(block)


# --------------------------------------------------------------------------
# Batching / prefetch plumbing
# --------------------------------------------------------------------------
def _assemble_batches(blocks: Iterator[Block], *, batch_size: int,
                      drop_last: bool, batch_format: str,
                      prefetch: int, device_put: bool,
                      local_shuffle_buffer_size: Optional[int] = None,
                      local_shuffle_seed: Optional[int] = None
                      ) -> Iterator[Any]:
    """Batcher → optional device_put → optional prefetch thread →
    format-on-consumer.  Formatting (e.g. pandas DataFrame build) runs
    on the caller's thread, never the prefetch daemon: pandas' lazy
    native init on a short-lived thread corrupts later pyarrow calls
    on other fresh threads (segfault observed under the test suite)."""
    if device_put and batch_format != "numpy":
        raise ValueError("device_put requires batch_format='numpy'")
    if local_shuffle_buffer_size is not None:
        if local_shuffle_buffer_size < 1:
            raise ValueError(
                "local_shuffle_buffer_size must be >= 1, got "
                f"{local_shuffle_buffer_size}")
        blocks = _local_shuffle_iter(blocks, local_shuffle_buffer_size,
                                     local_shuffle_seed)
    it = _batch_iterator(blocks, batch_size, drop_last)
    if device_put:
        it = _device_put_iter(it)
    if prefetch > 0:
        it = _prefetch_iter(it, prefetch)
    if batch_format == "numpy":
        return it
    return (_format_batch(b, batch_format) for b in it)


def _batch_iterator(blocks: Iterator[Block], batch_size: int,
                    drop_last: bool) -> Iterator[Block]:
    """Re-chunk a block stream into exact-size numpy batches
    (reference: _internal/batcher.py).  Batches are numpy views into
    the merged buffer (an offset walks the block; only the sub-batch
    tail is ever copied into the next merge), so a single huge block
    costs O(rows), not O(rows²/batch_size)."""
    merged: Block = {}
    offset = 0
    for block in blocks:
        if not merged or offset >= BlockAccessor.num_rows(merged):
            merged, offset = block, 0
        else:
            tail = BlockAccessor.slice(merged, offset,
                                       BlockAccessor.num_rows(merged))
            merged, offset = BlockAccessor.concat([tail, block]), 0
        while BlockAccessor.num_rows(merged) - offset >= batch_size:
            yield BlockAccessor.slice(merged, offset,
                                      offset + batch_size)
            offset += batch_size
    leftover = (BlockAccessor.num_rows(merged) - offset
                if merged else 0)
    if leftover > 0 and not drop_last:
        yield BlockAccessor.slice(merged, offset, offset + leftover)


def _local_shuffle_iter(blocks: Iterator[Block], buffer_rows: int,
                        seed: Optional[int]) -> Iterator[Block]:
    """Rolling within-shard shuffle (reference: iter_batches
    ``local_shuffle_buffer_size`` → ShufflingBatcher): rows pool into
    a buffer until it holds at least ``buffer_rows``, then the pooled
    rows are permuted and the surplus beyond half a buffer is emitted
    — every emitted row was mixed across a window of at least
    ``buffer_rows`` rows, at memcpy cost instead of an exchange."""
    rng = np.random.default_rng(seed)
    hold: Optional[Block] = None
    for block in blocks:
        hold = block if hold is None else \
            BlockAccessor.concat([hold, block])
        n = BlockAccessor.num_rows(hold)
        if n >= buffer_rows:
            hold = BlockAccessor.take(hold, rng.permutation(n))
            keep = buffer_rows // 2
            yield BlockAccessor.slice(hold, 0, n - keep)
            hold = dict(BlockAccessor.slice(hold, n - keep, n)) \
                if keep else None
    if hold is not None and BlockAccessor.num_rows(hold):
        n = BlockAccessor.num_rows(hold)
        yield BlockAccessor.take(hold, rng.permutation(n))


def _format_batch(batch: Block, batch_format: str) -> Any:
    if batch_format == "numpy":
        return batch
    if batch_format == "pandas":
        return BlockAccessor.to_pandas(batch)
    raise ValueError(f"unknown batch_format {batch_format!r}")


def _device_put_iter(batches: Iterator[Block]) -> Iterator[Any]:
    """Move batches to the default jax device, one ahead of the consumer
    (host→HBM transfer overlaps the consumer's current step)."""
    import jax

    pending = None
    for batch in batches:
        nxt = jax.device_put(batch)
        if pending is not None:
            yield pending
        pending = nxt
    if pending is not None:
        yield pending


def _prefetch_iter(it: Iterator[Any], depth: int) -> Iterator[Any]:
    """Run the upstream iterator in a daemon thread with a bounded
    queue (reference: block_batching prefetcher).  An abandoned
    consumer (break / GC) stops the pump via the stop flag, so no
    thread stays blocked holding device batches."""
    import queue as _queue

    q: "_queue.Queue[Any]" = _queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def pump():
        try:
            for item in it:
                if not put(item):
                    return
            put(_END)
        except BaseException as e:  # noqa: BLE001 — surface to consumer
            put(e)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except _queue.Empty:
            pass


# --------------------------------------------------------------------------
# Read API (reference: read_api.py)
# --------------------------------------------------------------------------
def read_datasource(source: Datasource, *, parallelism: int = -1
                    ) -> Dataset:
    return Dataset([Read(source, parallelism)])


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def from_items(items: Sequence[Any]) -> Dataset:
    return read_datasource(ItemsDatasource(items))


def from_blocks(blocks: List[Block]) -> Dataset:
    return read_datasource(BlocksDatasource(blocks))


def from_numpy(arrays: Union[np.ndarray, Dict[str, np.ndarray]]) -> Dataset:
    if isinstance(arrays, dict):
        return from_blocks([arrays])
    return from_blocks([{"data": np.asarray(arrays)}])


def from_pandas(df) -> Dataset:
    return from_blocks([BlockAccessor.from_pandas(df)])


def from_arrow(table) -> Dataset:
    return from_blocks([BlockAccessor.from_arrow(table)])


def read_parquet(paths, *, columns=None, parallelism: int = -1) -> Dataset:
    return read_datasource(parquet_datasource(paths, columns=columns),
                           parallelism=parallelism)


def read_csv(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(csv_datasource(paths, **kw),
                           parallelism=parallelism)


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(json_datasource(paths),
                           parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(numpy_datasource(paths),
                           parallelism=parallelism)


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    """TFRecord files of tf.train.Example protos (reference:
    read_api.read_tfrecords; codec is native — data/tfrecords.py)."""
    from .datasource import tfrecords_datasource

    return read_datasource(tfrecords_datasource(paths),
                           parallelism=parallelism)


def read_images(paths, *, size=None, mode=None,
                parallelism: int = -1) -> Dataset:
    """Image files → rows {"image": HWC array, "path"} (reference:
    read_api.read_images).  ``size=(w, h)`` resizes; ``mode`` converts
    (e.g. "RGB")."""
    from .datasource import image_datasource

    return read_datasource(image_datasource(paths, size=size, mode=mode),
                           parallelism=parallelism)
