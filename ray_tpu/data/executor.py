"""Logical plan, operator fusion, and the streaming executor.

Reference shape (SURVEY.md §2.4): Dataset facade holds a lazy logical plan
(data/_internal/logical/interfaces/logical_plan.py:10), an optimizer fuses
adjacent map stages (logical/rules/operator_fusion.py), the planner lowers
to physical operators, and a ``StreamingExecutor`` scheduling loop
(execution/streaming_executor.py:47,219,269 +
streaming_executor_state.py:395,533) dispatches block tasks with
backpressure.

TPU-first redesign: the executor is a *pull-based generator* rather than a
push-loop thread — the consumer (batcher / device-prefetch iterator) pulls,
and dispatch happens exactly as fast as consumption allows, which is the
backpressure policy (bounded in-flight tasks + bounded ordered-output
buffer).  Map chains are fused into a single ``ray_tpu`` task per input
block, so a read→map_batches→filter pipeline costs one task per block.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .block import Block, BlockAccessor, BlockMetadata
from .context import DataContext
from .datasource import Datasource, ReadTask

# A transform maps one block to zero-or-more blocks.
Transform = Callable[[Block], List[Block]]


# --------------------------------------------------------------------------
# Logical ops
# --------------------------------------------------------------------------
class LogicalOp:
    name = "op"

    def fused_transform(self) -> Optional[Transform]:
        """Return a per-block transform if this op is fusible into a map
        chain, else None (barrier op)."""
        return None


class Read(LogicalOp):
    name = "Read"

    def __init__(self, source: Datasource, parallelism: int = -1):
        self.source = source
        self.parallelism = parallelism


class MapBlocks(LogicalOp):
    """Fusible per-block transform: Map / MapBatches / Filter / FlatMap
    all normalize to this (reference: zero-copy map fusion rule)."""

    def __init__(self, name: str, transform: Transform):
        self.name = name
        self.transform = transform

    def fused_transform(self) -> Transform:
        return self.transform


class AllToAll(LogicalOp):
    """Barrier op: needs every upstream block at once
    (reference: _internal/planner/exchange/ — repartition, shuffle, sort)."""

    def __init__(self, name: str,
                 fn: Callable[[List[Block], DataContext], List[Block]]):
        self.name = name
        self.fn = fn


class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, n: int):
        self.n = n


# --------------------------------------------------------------------------
# Per-op runtime stats (reference: _internal/stats.py → ds.stats())
# --------------------------------------------------------------------------
class OpStats:
    def __init__(self, name: str):
        self.name = name
        self.num_tasks = 0
        self.num_blocks = 0
        self.num_rows = 0
        self.wall_s = 0.0

    def line(self) -> str:
        return (f"{self.name}: {self.num_tasks} tasks, "
                f"{self.num_blocks} blocks, {self.num_rows} rows, "
                f"{self.wall_s:.3f}s wall")


class PlanStats:
    def __init__(self):
        self.ops: List[OpStats] = []
        self.start = time.perf_counter()
        self.total_s = 0.0

    def summary(self) -> str:
        lines = [s.line() for s in self.ops]
        lines.append(f"total: {self.total_s:.3f}s")
        return "\n".join(lines)


# --------------------------------------------------------------------------
# Remote task bodies
# --------------------------------------------------------------------------
def _run_read(read_task: ReadTask, transforms: Sequence[Transform]
              ) -> List[Block]:
    blocks = read_task()
    return _apply(blocks, transforms)


def _run_map(block: Block, transforms: Sequence[Transform]) -> List[Block]:
    return _apply([block], transforms)


def _apply(blocks: List[Block], transforms: Sequence[Transform]
           ) -> List[Block]:
    for t in transforms:
        nxt: List[Block] = []
        for b in blocks:
            nxt.extend(t(b))
        blocks = nxt
    return [b for b in blocks if BlockAccessor.num_rows(b) > 0]


# --------------------------------------------------------------------------
# Physical plan: alternating [inputs] -> map chain -> barrier -> map chain...
# --------------------------------------------------------------------------
class _MapPhase:
    def __init__(self, names: List[str], transforms: List[Transform]):
        self.names = names
        self.transforms = transforms


def compile_plan(ops: Sequence[LogicalOp]
                 ) -> Tuple[Read, List[Any], Optional[int]]:
    """Fuse the op chain into phases.  Returns (read, phases, limit) where
    phases alternate _MapPhase / AllToAll; a trailing Limit is lifted into
    a streaming row cap (reference: limit pushdown rule)."""
    if not ops or not isinstance(ops[0], Read):
        raise ValueError("plan must start with a Read op")
    read = ops[0]
    phases: List[Any] = []
    cur_names: List[str] = []
    cur_tfs: List[Transform] = []
    limit: Optional[int] = None
    for op in ops[1:]:
        tf = op.fused_transform()
        if tf is not None:
            cur_names.append(op.name)
            cur_tfs.append(tf)
        elif isinstance(op, Limit):
            # Only a limit with nothing after it can stream; a limit
            # mid-plan becomes a truncating barrier.
            if op is ops[-1]:
                limit = op.n
            else:
                n = op.n
                phases.append(_MapPhase(cur_names, cur_tfs))
                cur_names, cur_tfs = [], []
                phases.append(AllToAll(
                    "Limit", lambda blocks, ctx, n=n: _truncate(blocks, n)))
        elif isinstance(op, AllToAll):
            phases.append(_MapPhase(cur_names, cur_tfs))
            cur_names, cur_tfs = [], []
            phases.append(op)
        else:
            raise TypeError(f"unknown logical op {op!r}")
    phases.append(_MapPhase(cur_names, cur_tfs))
    return read, phases, limit


def _truncate(blocks: List[Block], n: int) -> List[Block]:
    out: List[Block] = []
    remaining = n
    for b in blocks:
        rows = BlockAccessor.num_rows(b)
        if rows <= remaining:
            out.append(b)
            remaining -= rows
        else:
            out.append(BlockAccessor.slice(b, 0, remaining))
            remaining = 0
        if remaining == 0:
            break
    return out


# --------------------------------------------------------------------------
# Streaming executor
# --------------------------------------------------------------------------
def execute_streaming(ops: Sequence[LogicalOp],
                      ctx: Optional[DataContext] = None,
                      stats: Optional[PlanStats] = None
                      ) -> Iterator[Block]:
    """Run the plan, yielding output blocks in order as they are produced.

    Backpressure: at most ``ctx.max_concurrency`` tasks in flight and at
    most ``ctx.output_buffer_blocks`` completed blocks buffered; when the
    consumer stops pulling, dispatch stops (reference:
    streaming_executor_state.py:533 select_operator_to_run).
    """
    import ray_tpu

    ctx = ctx or DataContext.get_current()
    read, phases, limit = compile_plan(ops)
    read_tasks = read.source.read_tasks(
        read.parallelism if read.parallelism > 0 else
        _default_parallelism(read, ctx))

    # First map phase fuses with the read (reference fuses Read+Map).
    first = phases[0]
    source: Iterator[Block] = _stream_phase(
        [("read", rt) for rt in read_tasks], first, ctx, stats,
        name="Read+" + "+".join(first.names) if first.names else "Read")
    i = 1
    while i < len(phases):
        barrier: AllToAll = phases[i]
        map_phase: _MapPhase = phases[i + 1]
        blocks = list(source)  # materialize at the barrier
        t0 = time.perf_counter()
        shuffled = barrier.fn(blocks, ctx)
        if stats is not None:
            s = OpStats(barrier.name)
            s.num_tasks = 1
            s.num_blocks = len(shuffled)
            s.num_rows = sum(BlockAccessor.num_rows(b) for b in shuffled)
            s.wall_s = time.perf_counter() - t0
            stats.ops.append(s)
        source = _stream_phase(
            [("block", b) for b in shuffled], map_phase, ctx, stats,
            name="+".join(map_phase.names) or "identity")
        i += 2

    rows_out = 0
    for block in source:
        if limit is not None:
            rows = BlockAccessor.num_rows(block)
            if rows_out + rows >= limit:
                yield BlockAccessor.slice(block, 0, limit - rows_out)
                source.close()
                break
            rows_out += rows
        yield block
    if stats is not None:
        stats.total_s = time.perf_counter() - stats.start


def _default_parallelism(read: Read, ctx: DataContext) -> int:
    n = read.source.estimated_num_rows()
    if n is None:
        return ctx.max_concurrency
    return max(1, min(ctx.max_concurrency * 2,
                      -(-n // ctx.target_block_rows)))


def _stream_phase(items: List[Tuple[str, Any]], phase: _MapPhase,
                  ctx: DataContext, stats: Optional[PlanStats],
                  name: str) -> Iterator[Block]:
    """Stream one fused map phase over its inputs as ray_tpu tasks."""
    import ray_tpu

    op_stats = OpStats(name)
    if stats is not None:
        stats.ops.append(op_stats)

    transforms = phase.transforms
    if not transforms and all(kind == "block" for kind, _ in items):
        # Identity phase over in-memory blocks: no tasks needed.
        def passthrough():
            for _, b in items:
                op_stats.num_blocks += 1
                op_stats.num_rows += BlockAccessor.num_rows(b)
                yield b
        return passthrough()

    remote_read = ray_tpu.remote(_run_read)
    remote_map = ray_tpu.remote(_run_map)

    def gen() -> Iterator[Block]:
        t_start = time.perf_counter()
        in_flight: Dict[Any, int] = {}   # ref -> seq
        done: Dict[int, List[Block]] = {}  # seq -> blocks awaiting yield
        next_dispatch = 0
        next_yield = 0
        try:
            while next_yield < len(items):
                while (next_dispatch < len(items)
                       and len(in_flight) < ctx.max_concurrency
                       and len(done) < ctx.output_buffer_blocks):
                    kind, payload = items[next_dispatch]
                    if kind == "read":
                        ref = remote_read.remote(payload, transforms)
                    else:
                        ref = remote_map.remote(payload, transforms)
                    in_flight[ref] = next_dispatch
                    next_dispatch += 1
                    op_stats.num_tasks += 1
                if in_flight:
                    ready, _ = ray_tpu.wait(
                        list(in_flight), num_returns=1,
                        timeout=ctx.wait_timeout_s)
                    for ref in ready:
                        done[in_flight.pop(ref)] = ray_tpu.get(ref)
                while next_yield in done:
                    for block in done.pop(next_yield):
                        op_stats.num_blocks += 1
                        op_stats.num_rows += BlockAccessor.num_rows(block)
                        yield block
                    next_yield += 1
        finally:
            op_stats.wall_s = time.perf_counter() - t_start
            for ref in in_flight:
                try:
                    ray_tpu.cancel(ref)
                except Exception:
                    pass

    return gen()
