"""Logical plan, operator fusion, and the streaming executor.

Reference shape (SURVEY.md §2.4): Dataset facade holds a lazy logical plan
(data/_internal/logical/interfaces/logical_plan.py:10), an optimizer fuses
adjacent map stages (logical/rules/operator_fusion.py), the planner lowers
to physical operators — task-pool maps (execution/operators/
map_operator.py:55), ACTOR-pool maps (actor_pool_map_operator.py:34), and
distributed exchanges (planner/exchange/ — the push-based shuffle,
push_based_shuffle_task_scheduler.py:590) — and a ``StreamingExecutor``
scheduling loop dispatches block tasks with backpressure.

TPU-first redesign:
- The executor is a *pull-based generator* rather than a push-loop
  thread — the consumer pulls, and dispatch happens exactly as fast as
  consumption allows (bounded in-flight tasks + bounded ordered-output
  buffer = the backpressure policy).
- Blocks stream BY REFERENCE: a map task's output block groups stay
  pinned on the executing node (object-plane primary copies); the
  driver holds location records and hands refs straight to downstream
  tasks, which pull node-to-node over the chunk protocol.  Values only
  materialize at the final consumption point.  Exchanges (shuffle /
  sort / repartition / groupby) are PUSH-BASED (data/exchange.py): map
  tasks hash/range-partition rows and push each fragment to its owning
  streaming reducer as produced — same-host over shm rings, cross-host
  over the striped DCN push sockets — so no intermediate data crosses
  the driver and reducers combine/spill incrementally.
"""

from __future__ import annotations

import threading
import time
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

from .block import Block, BlockAccessor, BlockMetadata
from .context import DataContext
from .datasource import Datasource, ReadTask

# A transform maps one block to zero-or-more blocks.
Transform = Callable[[Block], List[Block]]


class ActorPoolStrategy:
    """Stateful compute for map_batches (reference:
    ActorPoolMapOperator, actor_pool_map_operator.py:34): the map fn is
    a CLASS, instantiated once per pool actor; batches round-robin over
    the least-loaded actors."""

    def __init__(self, size: int = 2):
        if size < 1:
            raise ValueError("actor pool size must be >= 1")
        self.size = size


# --------------------------------------------------------------------------
# Logical ops
# --------------------------------------------------------------------------
class LogicalOp:
    name = "op"

    def fused_transform(self) -> Optional[Transform]:
        """Return a per-block transform if this op is fusible into a map
        chain, else None (barrier op)."""
        return None


class Read(LogicalOp):
    name = "Read"

    def __init__(self, source: Datasource, parallelism: int = -1):
        self.source = source
        self.parallelism = parallelism


class MapBlocks(LogicalOp):
    """Fusible per-block transform: Map / MapBatches / Filter / FlatMap
    all normalize to this (reference: zero-copy map fusion rule)."""

    def __init__(self, name: str, transform: Transform):
        self.name = name
        self.transform = transform

    def fused_transform(self) -> Transform:
        return self.transform


class ActorMapBlocks(LogicalOp):
    """Actor-pool map stage: fn_class instantiated per pool actor."""

    def __init__(self, name: str, fn_class: type, fn_args: Tuple,
                 fn_kwargs: Dict[str, Any], batch_size: Optional[int],
                 compute: ActorPoolStrategy):
        self.name = name
        self.fn_class = fn_class
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs
        self.batch_size = batch_size
        self.compute = compute


class Exchange(LogicalOp):
    """Distributed all-to-all, executed push-based (data/exchange.py):
    map tasks run ``partition_fn`` per block and push fragments to
    streaming reducer actors as produced; each reducer finalizes its
    owned output partitions with ``merge_fn`` — or, when ``combine``
    is given, folds every arriving fragment into a running partial
    state (groupby aggregates) and never buffers raw rows.
    ``sample_fn`` (optional) runs per input group first; ``bounds_fn``
    reduces the samples driver-side into the small partition spec
    (e.g. sort range bounds)."""

    def __init__(self, name: str, partition_fn, merge_fn, n_out: int = -1,
                 sample_fn=None, bounds_fn=None,
                 needs_offsets: bool = False, combine=None):
        self.name = name
        self.partition_fn = partition_fn
        self.merge_fn = merge_fn
        self.n_out = n_out
        self.sample_fn = sample_fn
        self.bounds_fn = bounds_fn
        # An object with ``add(state, blocks) -> state`` and
        # ``finalize(state, spec, part_idx) -> List[Block]``: the
        # reducers' incremental-combine mode.
        self.combine = combine
        # True when partition_fn consumes exact global row offsets /
        # totals (repartition); forces the sample round even without a
        # sample_fn.
        self.needs_offsets = needs_offsets or sample_fn is not None


class ZipOp(LogicalOp):
    """Barrier: column-concatenate this plan's rows with another
    plan's rows, position-aligned (reference: Dataset.zip →
    ZipOperator).  Row counts must match — checked driver-side from a
    metadata round before any block moves."""

    name = "Zip"

    def __init__(self, other_ops: List["LogicalOp"]):
        self.other_ops = other_ops


class UnionOp(LogicalOp):
    """Barrier: append other plans' blocks after this plan's
    (reference: Dataset.union).  Column sets must agree — checked via
    a schema probe before the streams interleave."""

    name = "Union"

    def __init__(self, others: List[List["LogicalOp"]]):
        self.others = others


class AllToAll(LogicalOp):
    """Driver-side barrier op (small data / tests); prefer Exchange."""

    def __init__(self, name: str,
                 fn: Callable[[List[Block], DataContext], List[Block]]):
        self.name = name
        self.fn = fn


class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, n: int):
        self.n = n


# --------------------------------------------------------------------------
# Per-op runtime stats (reference: _internal/stats.py → ds.stats())
# --------------------------------------------------------------------------
class OpStats:
    def __init__(self, name: str):
        self.name = name
        self.num_tasks = 0
        self.num_blocks = 0
        self.num_rows = 0
        self.wall_s = 0.0

    def line(self) -> str:
        return (f"{self.name}: {self.num_tasks} tasks, "
                f"{self.num_blocks} blocks, {self.num_rows} rows, "
                f"{self.wall_s:.3f}s wall")


class PlanStats:
    def __init__(self):
        self.ops: List[OpStats] = []
        self.start = time.perf_counter()
        self.total_s = 0.0

    def summary(self) -> str:
        lines = [s.line() for s in self.ops]
        lines.append(f"total: {self.total_s:.3f}s")
        return "\n".join(lines)


# --------------------------------------------------------------------------
# Remote task bodies.  Map tasks return (group, meta): the group (the
# heavy payload) stays remote; meta is tiny and inlines to the driver.
# --------------------------------------------------------------------------
def _run_read(read_task: ReadTask, transforms: Sequence[Transform]):
    blocks = _apply(read_task(), transforms)
    return blocks, _meta(blocks)


def _run_map(upstream, transforms: Sequence[Transform]):
    # ``upstream`` is the resolved (group, meta) result of the feeding
    # task (the ref was passed as an arg; the runtime materialized it
    # here, node-to-node).
    group = upstream[0] if isinstance(upstream, tuple) else upstream
    blocks = _apply(list(group), transforms)
    return blocks, _meta(blocks)


def _meta(blocks: List[Block]) -> Dict[str, int]:
    return {"blocks": len(blocks),
            "rows": sum(BlockAccessor.num_rows(b) for b in blocks)}


def _apply(blocks: List[Block], transforms: Sequence[Transform]
           ) -> List[Block]:
    for t in transforms:
        nxt: List[Block] = []
        for b in blocks:
            nxt.extend(t(b))
        blocks = nxt
    return [b for b in blocks if BlockAccessor.num_rows(b) > 0]


class _PoolWorker:
    """Actor-pool map worker: holds one instance of the user's class."""

    def __init__(self, fn_class, fn_args, fn_kwargs):
        self.fn = fn_class(*fn_args, **fn_kwargs)

    def run(self, group, batch_size: Optional[int]):
        if isinstance(group, _RefGroup):
            group = group.resolve()
        out: List[Block] = []
        for block in group:
            if batch_size is None:
                out.append(BlockAccessor.validate(self.fn(block)))
                continue
            n = BlockAccessor.num_rows(block)
            for lo in range(0, n, batch_size):
                piece = BlockAccessor.slice(block, lo,
                                            min(lo + batch_size, n))
                out.append(BlockAccessor.validate(self.fn(piece)))
        out = [b for b in out if BlockAccessor.num_rows(b) > 0]
        return out, _meta(out)


# --------------------------------------------------------------------------
# Physical plan: alternating map-chain / barrier phases
# --------------------------------------------------------------------------
class _MapPhase:
    def __init__(self, names: List[str], transforms: List[Transform]):
        self.names = names
        self.transforms = transforms


def compile_plan(ops: Sequence[LogicalOp]
                 ) -> Tuple[Read, List[Any], Optional[int]]:
    """Fuse the op chain into phases.  Returns (read, phases, limit):
    phases alternate _MapPhase with barrier ops (Exchange / AllToAll /
    ActorMapBlocks); a trailing Limit is lifted into a streaming row cap
    (reference: limit pushdown rule)."""
    if not ops or not isinstance(ops[0], Read):
        raise ValueError("plan must start with a Read op")
    read = ops[0]
    phases: List[Any] = []
    cur_names: List[str] = []
    cur_tfs: List[Transform] = []
    limit: Optional[int] = None

    def flush():
        nonlocal cur_names, cur_tfs
        phases.append(_MapPhase(cur_names, cur_tfs))
        cur_names, cur_tfs = [], []

    for op in ops[1:]:
        tf = op.fused_transform()
        if tf is not None:
            cur_names.append(op.name)
            cur_tfs.append(tf)
        elif isinstance(op, Limit):
            # Only a limit with nothing after it can stream; a limit
            # mid-plan becomes a truncating barrier.
            if op is ops[-1]:
                limit = op.n
            else:
                n = op.n
                flush()
                phases.append(AllToAll(
                    "Limit", lambda blocks, ctx, n=n: _truncate(blocks, n)))
        elif isinstance(op, (AllToAll, Exchange, ActorMapBlocks,
                             ZipOp, UnionOp)):
            flush()
            phases.append(op)
        else:
            raise TypeError(f"unknown logical op {op!r}")
    flush()
    return read, phases, limit


def _truncate(blocks: List[Block], n: int) -> List[Block]:
    out: List[Block] = []
    remaining = n
    for b in blocks:
        rows = BlockAccessor.num_rows(b)
        if rows <= remaining:
            out.append(b)
            remaining -= rows
        else:
            out.append(BlockAccessor.slice(b, 0, remaining))
            remaining = 0
        if remaining == 0:
            break
    return out


# --------------------------------------------------------------------------
# Streaming executor (refs end to end)
# --------------------------------------------------------------------------
def execute_streaming(ops: Sequence[LogicalOp],
                      ctx: Optional[DataContext] = None,
                      stats: Optional[PlanStats] = None
                      ) -> Iterator[Block]:
    """Run the plan, yielding output blocks in order as they complete.
    Intermediate results stream between phases as ObjectRefs — block
    values materialize only here, at final consumption."""
    import ray_tpu

    gen = _execute_refs(ops, ctx, stats)
    rows_cap = gen.send(None)  # prime; first yield carries the limit
    rows_out = 0
    try:
        for ref in gen:
            group, _meta_ignored = ray_tpu.get(ref)
            for block in group:
                if rows_cap is not None:
                    rows = BlockAccessor.num_rows(block)
                    if rows_out + rows >= rows_cap:
                        yield BlockAccessor.slice(block, 0,
                                                  rows_cap - rows_out)
                        gen.close()
                        return
                    rows_out += rows
                yield block
    finally:
        gen.close()
        if stats is not None:
            stats.total_s = time.perf_counter() - stats.start


def _execute_refs(ops, ctx, stats):
    """Generator: first yield is the streaming row cap (or None), then
    one ObjectRef per output group, in order."""
    import ray_tpu

    ctx = ctx or DataContext.get_current()
    read, phases, limit = compile_plan(ops)
    yield limit

    read_tasks = read.source.read_tasks(
        read.parallelism if read.parallelism > 0 else
        _default_parallelism(read, ctx))

    # First map phase fuses with the read (reference fuses Read+Map).
    first = phases[0]
    source = _stream_phase(
        [("read", rt) for rt in read_tasks], first, ctx, stats,
        name="Read+" + "+".join(first.names) if first.names else "Read")
    i = 1
    while i < len(phases):
        barrier = phases[i]
        map_phase: _MapPhase = phases[i + 1]
        if isinstance(barrier, ActorMapBlocks):
            source = _stream_actor_pool(source, barrier, ctx, stats)
        elif isinstance(barrier, Exchange):
            from .exchange import exchange_streaming

            source = exchange_streaming(source, barrier, ctx, stats)
        elif isinstance(barrier, ZipOp):
            source = _stream_zip(source, barrier, ctx, stats)
        elif isinstance(barrier, UnionOp):
            source = _stream_union(source, barrier, ctx, stats)
        else:
            source = _run_driver_barrier(source, barrier, ctx, stats)
        if map_phase.transforms:
            source = _stream_phase(
                [("ref", r) for r in source], map_phase, ctx, stats,
                name="+".join(map_phase.names))
        i += 2
    yield from source


def _default_parallelism(read: Read, ctx: DataContext) -> int:
    n = read.source.estimated_num_rows()
    if n is None:
        return ctx.max_concurrency
    return max(1, min(ctx.max_concurrency * 2,
                      -(-n // ctx.target_block_rows)))


def _stream_phase(items, phase: _MapPhase, ctx: DataContext,
                  stats: Optional[PlanStats], name: str):
    """Stream one fused map phase: yields one ref per input item, in
    order, with bounded in-flight dispatch.  ``items`` entries are
    ("read", ReadTask) or ("ref", upstream group ref); upstream refs
    are handed to the task as ARGS, so the block values move node to
    node, never through the driver."""
    import ray_tpu

    op_stats = OpStats(name)
    if stats is not None:
        stats.ops.append(op_stats)

    transforms = phase.transforms

    remote_read = ray_tpu.remote(_run_read)
    remote_map = ray_tpu.remote(_run_map)

    def gen():
        # Lazy upstream consumption: a map phase behind a barrier
        # starts dispatching as soon as the FIRST upstream result
        # exists instead of draining the whole barrier — the
        # pipelining this executor exists for.
        t_start = time.perf_counter()
        it = iter(items)
        exhausted = False
        in_flight: Dict[Any, int] = {}   # ref -> seq
        group_refs: Dict[int, Any] = {}  # seq -> group ref
        done: Dict[int, Any] = {}        # seq -> completion flag
        next_dispatch = 0
        next_yield = 0
        try:
            while True:
                while (not exhausted
                       and len(in_flight) < ctx.max_concurrency
                       and len(done) < ctx.output_buffer_blocks):
                    try:
                        kind, payload = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    if kind == "read":
                        ref = remote_read.remote(payload, transforms)
                    else:
                        ref = remote_map.remote(payload, transforms)
                    # The task returns (group, meta); the driver waits
                    # on the combined ref but only materializes meta at
                    # yield time — big groups stay remote primaries.
                    in_flight[ref] = next_dispatch
                    group_refs[next_dispatch] = ref
                    next_dispatch += 1
                    op_stats.num_tasks += 1
                if exhausted and not in_flight and next_yield >= \
                        next_dispatch:
                    return
                if in_flight:
                    ready, _ = ray_tpu.wait(
                        list(in_flight), num_returns=1,
                        timeout=ctx.wait_timeout_s)
                    for ref in ready:
                        done[in_flight.pop(ref)] = True
                while next_yield in done:
                    done.pop(next_yield)
                    ref = group_refs.pop(next_yield)
                    op_stats.num_blocks += 1
                    next_yield += 1
                    yield ref
        finally:
            op_stats.wall_s = time.perf_counter() - t_start
            for ref in in_flight:
                try:
                    ray_tpu.cancel(ref)
                except Exception:
                    pass

    return gen()


def _stream_actor_pool(source, op: ActorMapBlocks, ctx, stats):
    """Actor-pool map: a pool of stateful workers; groups dispatch to
    the least-loaded worker (actor_pool_map_operator.py:34)."""
    import ray_tpu

    op_stats = OpStats(f"ActorMap[{op.name}]")
    if stats is not None:
        stats.ops.append(op_stats)
    Worker = ray_tpu.remote(_PoolWorker)
    pool = [Worker.remote(op.fn_class, op.fn_args, op.fn_kwargs)
            for _ in range(op.compute.size)]
    load = [0] * len(pool)

    def gen():
        t0 = time.perf_counter()
        pending: List[Tuple[Any, int]] = []  # (ref, worker) in order
        try:
            upstream = iter(source)
            exhausted = False
            next_up = None
            while True:
                while (not exhausted
                       and len(pending) < ctx.max_concurrency):
                    try:
                        next_up = next(upstream)
                    except StopIteration:
                        exhausted = True
                        break
                    w = load.index(min(load))
                    load[w] += 1
                    # Pass the UPSTREAM result ref; the worker unwraps
                    # the group itself (values fetch node-to-node).
                    ref = pool[w].run.remote(
                        _RefGroup(next_up), op.batch_size)
                    pending.append((ref, w))
                    op_stats.num_tasks += 1
                if not pending:
                    return
                ref, w = pending.pop(0)
                # Wait for completion (ordered yield).
                ray_tpu.wait([ref], num_returns=1, timeout=None)
                load[w] -= 1
                op_stats.num_blocks += 1
                yield ref
        finally:
            op_stats.wall_s = time.perf_counter() - t0
            for w in pool:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass

    return gen()


class _RefGroup:
    """Marker wrapper: an upstream (group, meta) ref whose group the
    receiving task unwraps (keeps worker signatures uniform)."""

    def __init__(self, ref):
        self.ref = ref

    def resolve(self) -> List[Block]:
        import ray_tpu

        group, _m = ray_tpu.get(self.ref)
        return group


def _resolve_groups(args):
    return [a.resolve() if isinstance(a, _RefGroup) else a for a in args]


def _run_sample_wrapped(group, sample_fn):
    blocks = _resolve_groups([group])[0]
    rows = sum(BlockAccessor.num_rows(b) for b in blocks)
    return rows, (sample_fn(blocks) if sample_fn is not None else None)


def _rows_of(group) -> int:
    blocks = _resolve_groups([group])[0]
    return sum(BlockAccessor.num_rows(b) for b in blocks)


def _schema_of(group):
    """Column names + dtype strings of the group's first non-empty
    block, or None (the union schema probe's unit)."""
    blocks = _resolve_groups([group])[0]
    for b in blocks:
        if BlockAccessor.num_rows(b):
            return {k: str(v) for k, v in
                    BlockAccessor.schema(b).items()}
    return None


def _zip_slice(left_group, lo, hi, right_groups, right_starts):
    """Zip one left group (global rows [lo, hi)) with the matching
    row range gathered from the overlapping right groups.  Colliding
    right column names get a ``_1`` suffix (reference zip
    convention)."""
    lblocks = _resolve_groups([left_group])[0]
    lb = BlockAccessor.concat(lblocks)
    pieces: List[Block] = []
    for g, start in zip(right_groups, right_starts):
        rb = BlockAccessor.concat(_resolve_groups([g])[0])
        n = BlockAccessor.num_rows(rb)
        s, e = max(lo - start, 0), min(hi - start, n)
        if e > s:
            pieces.append(BlockAccessor.slice(rb, s, e))
    rb = BlockAccessor.concat(pieces)
    out: Block = dict(lb)
    for k, v in rb.items():
        out[k if k not in lb else f"{k}_1"] = v
    blocks = [out]
    return blocks, _meta(blocks)


def _stream_zip(source, op: ZipOp, ctx, stats):
    """Driver-coordinated barrier: one metadata round (row counts per
    group, both sides), then one zip-slice task per LEFT group that
    gathers its row range from the overlapping right groups — block
    values still move node-to-node."""
    import ray_tpu

    from ..exceptions import ZipLengthMismatchError

    op_stats = OpStats("Zip")
    if stats is not None:
        stats.ops.append(op_stats)
    t0 = time.perf_counter()
    left = list(source)
    rgen = _execute_refs(op.other_ops, ctx, stats)
    rgen.send(None)  # prime; a nested plan's limit cannot stream
    right = list(rgen)
    remote_rows = ray_tpu.remote(_rows_of)
    lrows = ray_tpu.get([remote_rows.remote(_RefGroup(r))
                         for r in left])
    rrows = ray_tpu.get([remote_rows.remote(_RefGroup(r))
                         for r in right])
    if sum(lrows) != sum(rrows):
        op_stats.wall_s = time.perf_counter() - t0
        raise ZipLengthMismatchError(sum(lrows), sum(rrows))
    loffs = np.cumsum([0] + lrows)
    roffs = list(np.cumsum([0] + rrows))
    remote_zip = ray_tpu.remote(_zip_slice)
    out_refs = []
    for i, ref in enumerate(left):
        lo, hi = int(loffs[i]), int(loffs[i + 1])
        if hi == lo:
            continue
        overlap = [(right[j], int(roffs[j]))
                   for j in range(len(right))
                   if roffs[j] < hi and roffs[j + 1] > lo]
        out_refs.append(remote_zip.remote(
            _RefGroup(ref), lo, hi,
            [_RefGroup(r) for r, _s in overlap],
            [s for _r, s in overlap]))
        op_stats.num_tasks += 1

    def gen():
        try:
            for ref in out_refs:
                ray_tpu.wait([ref], num_returns=1, timeout=None)
                op_stats.num_blocks += 1
                yield ref
        finally:
            op_stats.wall_s = time.perf_counter() - t0

    return gen()


def _stream_union(source, op: UnionOp, ctx, stats):
    """Append the other plans' ref streams after this one, after a
    schema probe confirms every source shares one column set."""
    import ray_tpu

    from ..exceptions import UnionSchemaError

    op_stats = OpStats("Union")
    if stats is not None:
        stats.ops.append(op_stats)
    t0 = time.perf_counter()
    streams = [list(source)]
    for other_ops in op.others:
        g = _execute_refs(other_ops, ctx, stats)
        g.send(None)
        streams.append(list(g))
    remote_schema = ray_tpu.remote(_schema_of)
    schemas = []
    for refs in streams:
        found = None
        for s in ray_tpu.get([remote_schema.remote(_RefGroup(r))
                              for r in refs]):
            if s is not None:
                found = s
                break
        schemas.append(found)
    base = next((s for s in schemas if s is not None), None)
    if base is not None:
        for s in schemas[1:]:
            if s is not None and set(s) != set(base):
                op_stats.wall_s = time.perf_counter() - t0
                raise UnionSchemaError(base, s)

    def gen():
        try:
            for refs in streams:
                for ref in refs:
                    op_stats.num_blocks += 1
                    yield ref
        finally:
            op_stats.wall_s = time.perf_counter() - t0

    return gen()


def _run_driver_barrier(source, barrier: AllToAll, ctx, stats):
    """Legacy driver-side barrier: materializes, applies, re-puts."""
    import ray_tpu

    op_stats = OpStats(barrier.name)
    if stats is not None:
        stats.ops.append(op_stats)
    t0 = time.perf_counter()
    blocks: List[Block] = []
    for ref in source:
        group, _m = ray_tpu.get(ref)
        blocks.extend(group)
    out = barrier.fn(blocks, ctx)
    op_stats.num_tasks = 1
    op_stats.num_blocks = len(out)
    op_stats.num_rows = sum(BlockAccessor.num_rows(b) for b in out)
    op_stats.wall_s = time.perf_counter() - t0
    return iter([ray_tpu.put(([b], _meta([b]))) for b in out])
