"""Grandfathering baseline: pre-existing findings recorded by
fingerprint (rule + path + symbol + message — no line numbers, so
unrelated edits don't invalidate entries).  The lint gate fails only
on NON-baselined findings; fixing a baselined one and regenerating
shrinks the file monotonically."""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Set

from .rules import Finding


def load(path: str) -> Set[str]:
    if not path or not os.path.exists(path):
        return set()
    with open(path, "r", encoding="utf-8") as f:
        blob = json.load(f)
    entries = blob.get("findings", []) if isinstance(blob, dict) else blob
    out: Set[str] = set()
    for e in entries:
        if isinstance(e, str):
            out.add(e)
        elif isinstance(e, dict) and "fingerprint" in e:
            out.add(e["fingerprint"])
    return out


def save(path: str, findings: Iterable[Finding]) -> int:
    """Write the CURRENT findings as the new baseline (sorted, one
    readable record per finding).  Returns the entry count."""
    records: List[Dict] = []
    seen: Set[str] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line)):
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        records.append({"rule": f.rule, "path": f.path,
                        "symbol": f.symbol, "message": f.message,
                        "fingerprint": f.fingerprint})
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": records}, f, indent=1)
        f.write("\n")
    return len(records)


def apply(findings: List[Finding], baselined: Set[str]) -> List[Finding]:
    """Mark findings whose fingerprint is grandfathered."""
    for f in findings:
        f.baselined = f.fingerprint in baselined
    return findings
