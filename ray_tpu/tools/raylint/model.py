"""The raylint project model: ONE parse of the whole package.

Every rule runs against this shared index instead of re-walking files:

- module index: dotted module name -> parsed AST + source lines
- function table: qualified name ("pkg.mod:Cls.meth") -> FuncInfo
- class table: lock/condition attributes (assignments of
  ``threading.Lock/RLock/Condition``), method sets, base names
- call graph: conservative name-based resolution (self-methods,
  module-local functions, imported symbols, project classes ->
  ``__init__``, plus a unique-method-name fallback for cross-class
  edges) — enough to chase ``blocking-under-lock`` transitively
- suppressions: ``# raylint: disable=<rule>[,<rule>] -- reason``
  parsed out of the raw source (AST drops comments)

The model is deliberately approximate where Python is dynamic: rules
prefer a small number of explainable false positives (silenced with a
reasoned ``disable``) over silent false negatives in the invariants
this framework actually depends on.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# disable comment syntax: "raylint: disable=<rules> -- <why>"
_SUPPRESS_RE = re.compile(
    r"#\s*raylint:\s*disable=([a-zA-Z0-9_,\- ]+?)"
    r"(?:\s*--\s*(?P<reason>\S.*))?\s*$")

_LOCK_FACTORIES = {"Lock", "RLock"}
_COND_FACTORIES = {"Condition"}


@dataclass
class Suppression:
    line: int
    rules: Set[str]
    reason: Optional[str]
    comment_only: bool  # whole line is the comment -> guards line+1


@dataclass
class ModuleInfo:
    name: str                      # dotted ("ray_tpu.cluster.head")
    path: str                      # absolute
    relpath: str                   # project-root relative
    tree: ast.Module
    lines: List[str]
    is_package: bool = False       # an __init__.py (relative imports
    #                                anchor at the package ITSELF)
    suppressions: List[Suppression] = field(default_factory=list)
    imports: Dict[str, str] = field(default_factory=dict)
    # module-level names bound to threading.Lock()/RLock()/Condition()
    locks: Set[str] = field(default_factory=set)
    conds: Set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    qualname: str                  # "pkg.mod:Cls"
    module: str
    name: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, str] = field(default_factory=dict)  # name->func qn
    lock_attrs: Set[str] = field(default_factory=set)
    cond_attrs: Set[str] = field(default_factory=set)


@dataclass
class FuncInfo:
    qualname: str                  # "pkg.mod:Cls.meth" / "pkg.mod:fn"
    module: str
    cls: Optional[str]             # enclosing class simple name
    name: str
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    line: int


class ProjectModel:
    """Parse ``root`` (a package directory) once and index it."""

    def __init__(self, root: str, package: Optional[str] = None):
        self.root = os.path.abspath(root)
        self.project_dir = os.path.dirname(self.root) or "."
        self.package = package or os.path.basename(self.root.rstrip("/"))
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        # bare function/method name -> qualnames defining it
        self.by_name: Dict[str, List[str]] = {}
        # call graph: func qualname -> [(callee qualname, line, via)]
        self.calls: Dict[str, List[Tuple[str, int, str]]] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        self._own_cache: Dict[int, List[ast.AST]] = {}
        self._load()
        self._index()
        self._build_call_graph()

    # ------------------------------------------------------------ loading
    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.project_dir)
                modname = self._modname(path)
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        src = f.read()
                    tree = ast.parse(src, filename=path)
                except (SyntaxError, UnicodeDecodeError, OSError) as e:
                    self.parse_errors.append((rel, str(e)))
                    continue
                info = ModuleInfo(name=modname, path=path, relpath=rel,
                                  tree=tree, lines=src.splitlines(),
                                  is_package=fn == "__init__.py")
                self._scan_suppressions(info)
                self._scan_imports(info)
                self.modules[modname] = info

    def _modname(self, path: str) -> str:
        rel = os.path.relpath(path, os.path.dirname(self.root))
        rel = rel[:-3] if rel.endswith(".py") else rel
        parts = rel.split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def _scan_suppressions(self, info: ModuleInfo) -> None:
        for i, line in enumerate(info.lines, start=1):
            if "raylint" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            info.suppressions.append(Suppression(
                line=i, rules=rules, reason=m.group("reason"),
                comment_only=line.strip().startswith("#")))

    def _scan_imports(self, info: ModuleInfo) -> None:
        """name -> fully-qualified target ("pkg.mod" or "pkg.mod.sym")."""
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    info.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(info, node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    info.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}"

    def _resolve_from(self, info: ModuleInfo,
                      node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        parts = info.name.split(".")
        # "from . import x" in a plain module drops the module's own
        # leaf; in a package __init__ the single dot IS the package
        # (its dotted name already lacks the "__init__" leaf), so a
        # package strips one level fewer.  Each extra dot climbs one
        # more package either way.
        drop = node.level - (1 if info.is_package else 0)
        if drop > len(parts):
            return None
        anchor = parts[:-drop] if drop else list(parts)
        if node.module:
            anchor = anchor + node.module.split(".")
        return ".".join(anchor) if anchor else None

    # ----------------------------------------------------------- indexing
    def _index(self) -> None:
        for info in self.modules.values():
            self._index_module_locks(info)
            for node in info.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._index_class(info, node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._index_func(info, node, cls=None)

    def _is_factory(self, info: ModuleInfo, call: ast.AST,
                    names: Set[str]) -> bool:
        """``threading.Lock()`` / ``Lock()`` (imported) value?"""
        if not isinstance(call, ast.Call):
            return False
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in names and \
                isinstance(f.value, ast.Name) and \
                info.imports.get(f.value.id, f.value.id) == "threading":
            return True
        if isinstance(f, ast.Name) and f.id in names and \
                info.imports.get(f.id, "").startswith("threading."):
            return True
        return False

    def _index_module_locks(self, info: ModuleInfo) -> None:
        for node in info.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if self._is_factory(info, node.value, _LOCK_FACTORIES):
                    info.locks.add(name)
                elif self._is_factory(info, node.value, _COND_FACTORIES):
                    info.conds.add(name)

    def _index_class(self, info: ModuleInfo, node: ast.ClassDef) -> None:
        qn = f"{info.name}:{node.name}"
        ci = ClassInfo(qualname=qn, module=info.name, name=node.name,
                       node=node,
                       bases=[b.id for b in node.bases
                              if isinstance(b, ast.Name)])
        self.classes[qn] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self._index_func(info, item, cls=node.name)
                ci.methods[item.name] = fi.qualname
        # lock attributes: "self.X = threading.Lock()" anywhere in the
        # class body (usually __init__, but not only)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t = sub.targets[0]
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    if self._is_factory(info, sub.value, _LOCK_FACTORIES):
                        ci.lock_attrs.add(t.attr)
                    elif self._is_factory(info, sub.value,
                                          _COND_FACTORIES):
                        ci.cond_attrs.add(t.attr)

    def _index_func(self, info: ModuleInfo, node, cls: Optional[str],
                    prefix: str = "") -> FuncInfo:
        base = f"{cls}." if cls else ""
        qn = f"{info.name}:{prefix}{base}{node.name}"
        fi = FuncInfo(qualname=qn, module=info.name, cls=cls,
                      name=node.name, node=node, line=node.lineno)
        self.functions[qn] = fi
        self.by_name.setdefault(node.name, []).append(qn)
        # nested defs become their own nodes (resolved by local name)
        self._index_nested(info, node, cls,
                           prefix=f"{prefix}{base}{node.name}.")
        return fi

    def _index_nested(self, info: ModuleInfo, func_node, cls,
                      prefix) -> None:
        """Index the defs DIRECTLY nested in ``func_node``; each level
        recurses with its own prefix, so ``outer.a.helper`` and
        ``outer.b.helper`` never collide (a collision would silently
        drop the second body from every rule's scan)."""
        for sub in self._direct_child_defs(func_node):
            qn = f"{info.name}:{prefix}{sub.name}"
            if qn in self.functions:
                # same name re-bound within one scope (rare):
                # disambiguate by line rather than drop the body
                qn = f"{qn}@{sub.lineno}"
            fi = FuncInfo(qualname=qn, module=info.name, cls=cls,
                          name=sub.name, node=sub, line=sub.lineno)
            self.functions[qn] = fi
            self.by_name.setdefault(sub.name, []).append(qn)
            self._index_nested(info, sub, cls,
                               prefix=f"{prefix}{sub.name}.")

    @staticmethod
    def _direct_child_defs(func_node):
        """FunctionDefs nested in ``func_node`` without crossing
        another function boundary (does descend into if/try/with/
        loops and class bodies)."""
        out = []
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                out.append(node)
                continue
            if isinstance(node, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    # --------------------------------------------------------- call graph
    def _build_call_graph(self) -> None:
        for fi in list(self.functions.values()):
            edges: List[Tuple[str, int, str]] = []
            info = self.modules[fi.module]
            for node in self.walk_own(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                target = self._resolve_call(info, fi, node)
                if target is not None:
                    edges.append((target, node.lineno,
                                  call_desc(node)))
            self.calls[fi.qualname] = edges

    def walk_own(self, func_node):
        """All nodes of a function body WITHOUT descending into nested
        function definitions (they execute elsewhere) or lambdas.
        Cached per node: every rule re-walks every function, and the
        traversal dominates the whole lint wall-clock otherwise."""
        cached = self._own_cache.get(id(func_node))
        if cached is not None:
            return cached
        out = []
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        self._own_cache[id(func_node)] = out
        return out

    def _resolve_call(self, info: ModuleInfo, fi: FuncInfo,
                      call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            return self._resolve_name(info, fi, f.id)
        if isinstance(f, ast.Attribute):
            # self.method(...)
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and fi.cls is not None:
                qn = self._method_on(info.name, fi.cls, f.attr)
                if qn is not None:
                    return qn
            # module_alias.func(...)
            if isinstance(f.value, ast.Name):
                target = info.imports.get(f.value.id)
                if target in self.modules:
                    mod = self.modules[target]
                    qn = f"{mod.name}:{f.attr}"
                    if qn in self.functions:
                        return qn
            # unique-method fallback: exactly one project definition of
            # this name -> conservative (class-blind) edge
            cands = self.by_name.get(f.attr, ())
            if len(cands) == 1:
                return cands[0]
        return None

    def _method_on(self, module: str, cls: str,
                   name: str) -> Optional[str]:
        """Method lookup on a class, following project-local bases."""
        seen: Set[str] = set()
        stack = [f"{module}:{cls}"]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            ci = self.classes.get(key)
            if ci is None:
                continue
            if name in ci.methods:
                return ci.methods[name]
            for base in ci.bases:
                # same module first, else any project class of the name
                if f"{ci.module}:{base}" in self.classes:
                    stack.append(f"{ci.module}:{base}")
                else:
                    stack.extend(k for k in self.classes
                                 if k.endswith(f":{base}"))
        return None

    def _resolve_name(self, info: ModuleInfo, fi: FuncInfo,
                      name: str) -> Optional[str]:
        # sibling nested function first (shares the enclosing prefix)
        prefix = fi.qualname.rsplit(".", 1)[0]
        for cand in (f"{prefix}.{name}", f"{fi.qualname}.{name}",
                     f"{info.name}:{name}"):
            if cand in self.functions:
                return cand
        imported = info.imports.get(name)
        if imported:
            # imported function...
            mod, _, sym = imported.rpartition(".")
            qn = f"{mod}:{sym}"
            if qn in self.functions:
                return qn
            # ...or imported project class -> its __init__
            ci = self.classes.get(qn)
            if ci and "__init__" in ci.methods:
                return ci.methods["__init__"]
        # class defined in this module -> __init__
        ci = self.classes.get(f"{info.name}:{name}")
        if ci and "__init__" in ci.methods:
            return ci.methods["__init__"]
        return None

    # --------------------------------------------------------- utilities
    def lock_context(self, info: ModuleInfo, fi: FuncInfo,
                     expr: ast.AST) -> Optional[Tuple[str, bool]]:
        """(lock name, is_condition) when ``expr`` (a with-item) is a
        known lock/condition object, else None.  Falls back to a name
        heuristic (``*_lock`` / ``*mutex*`` / ``*_cond``) for locks
        passed in from elsewhere."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fi.cls is not None:
            ci = self.classes.get(f"{fi.module}:{fi.cls}")
            if ci is not None:
                if expr.attr in ci.lock_attrs:
                    return expr.attr, False
                if expr.attr in ci.cond_attrs:
                    return expr.attr, True
            return _lock_by_name(expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in info.locks:
                return expr.id, False
            if expr.id in info.conds:
                return expr.id, True
            return _lock_by_name(expr.id)
        return None


def _lock_by_name(name: str) -> Optional[Tuple[str, bool]]:
    low = name.lower()
    if low.endswith("_cond") or low.endswith("cond"):
        return name, True
    if low.endswith("lock") or "mutex" in low:
        return name, False
    return None


def call_desc(call: ast.Call) -> str:
    """Short printable form of a call target ("self.head.call")."""
    try:
        return ast.unparse(call.func)
    except Exception:
        return "<call>"
