"""The raylint project model: ONE parse of the whole package.

Every rule runs against this shared index instead of re-walking files:

- module index: dotted module name -> parsed AST + source lines
- function table: qualified name ("pkg.mod:Cls.meth") -> FuncInfo
- class table: lock/condition attributes (assignments of
  ``threading.Lock/RLock/Condition``), method sets, base names
- call graph: conservative name-based resolution (self-methods,
  module-local functions, imported symbols, project classes ->
  ``__init__``, plus a unique-method-name fallback for cross-class
  edges) — enough to chase ``blocking-under-lock`` transitively
- suppressions: ``# raylint: disable=<rule>[,<rule>] -- reason``
  parsed out of the raw source (AST drops comments)

The model is deliberately approximate where Python is dynamic: rules
prefer a small number of explainable false positives (silenced with a
reasoned ``disable``) over silent false negatives in the invariants
this framework actually depends on.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# disable comment syntax: "raylint: disable=<rules> -- <why>"
_SUPPRESS_RE = re.compile(
    r"#\s*raylint:\s*disable=([a-zA-Z0-9_,\- ]+?)"
    r"(?:\s*--\s*(?P<reason>\S.*))?\s*$")

_LOCK_FACTORIES = {"Lock", "RLock"}
_COND_FACTORIES = {"Condition"}


@dataclass
class Suppression:
    line: int
    rules: Set[str]
    reason: Optional[str]
    comment_only: bool  # whole line is the comment -> guards line+1


@dataclass
class ModuleInfo:
    name: str                      # dotted ("ray_tpu.cluster.head")
    path: str                      # absolute
    relpath: str                   # project-root relative
    tree: ast.Module
    lines: List[str]
    is_package: bool = False       # an __init__.py (relative imports
    #                                anchor at the package ITSELF)
    suppressions: List[Suppression] = field(default_factory=list)
    imports: Dict[str, str] = field(default_factory=dict)
    # module-level names bound to threading.Lock()/RLock()/Condition()
    locks: Set[str] = field(default_factory=set)
    conds: Set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    qualname: str                  # "pkg.mod:Cls"
    module: str
    name: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, str] = field(default_factory=dict)  # name->func qn
    lock_attrs: Set[str] = field(default_factory=set)
    cond_attrs: Set[str] = field(default_factory=set)
    # cond attr -> the lock attr it WRAPS ("self._cond =
    # threading.Condition(self._lock)"): the condition IS that lock
    # for ordering purposes — acquiring one while holding the other
    # is reentrant, not an inversion.
    cond_alias: Dict[str, str] = field(default_factory=dict)


@dataclass
class CallEdge:
    """One call-graph edge with its resolution confidence.  ``kind``:
    "self" (self.method), "local" (sibling/nested def), "module"
    (module-local function or alias.func into a project module),
    "import" (imported project symbol), "init" (class -> __init__),
    "fallback" (unique-method-name guess — class-blind, the edge the
    lock-set propagation must NOT trust)."""
    target: str
    line: int
    via: str
    kind: str


@dataclass
class FuncInfo:
    qualname: str                  # "pkg.mod:Cls.meth" / "pkg.mod:fn"
    module: str
    cls: Optional[str]             # enclosing class simple name
    name: str
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    line: int


class ProjectModel:
    """Parse ``root`` (a package directory) once and index it."""

    def __init__(self, root: str, package: Optional[str] = None):
        self.root = os.path.abspath(root)
        self.project_dir = os.path.dirname(self.root) or "."
        self.package = package or os.path.basename(self.root.rstrip("/"))
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        # bare function/method name -> qualnames defining it
        self.by_name: Dict[str, List[str]] = {}
        # call graph: func qualname -> [(callee qualname, line, via)]
        # (legacy 3-tuple view; call_edges carries the resolution kind)
        self.calls: Dict[str, List[Tuple[str, int, str]]] = {}
        self.call_edges: Dict[str, List[CallEdge]] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        self._own_cache: Dict[int, List[ast.AST]] = {}
        # (call-node id, enclosing fn qualname) -> resolved
        # (target, kind) | None.  Resolution (inheritance walks,
        # import chasing) is re-requested for the same Call node by
        # the call-graph build, the lock-set scan, the raise
        # inference, and the try indexing — memoize it.  Node ids
        # stay valid for the model's lifetime (ModuleInfo pins every
        # tree); the qualname qualifier matters because the parse
        # memo SHARES one AST between byte-identical files, so the
        # same node resolves under different modules' import/class
        # contexts.
        self._edge_cache: Dict[Tuple[int, str],
                               Optional[Tuple[str, str]]] = {}
        self._locks: Optional[LockAnalysis] = None
        self._load()
        self._index()
        self._build_call_graph()

    def lock_analysis(self) -> "LockAnalysis":
        """The interprocedural lock-set model, built once on demand
        (the lock-order and wait rules share it, and the CLI dumps
        its graph)."""
        if self._locks is None:
            self._locks = LockAnalysis(self)
        return self._locks

    # ------------------------------------------------------------ loading
    def _load(self) -> None:
        cache = _ParseCache.open(self.project_dir)
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.project_dir)
                modname = self._modname(path)
                try:
                    with open(path, "rb") as f:
                        raw = f.read()
                    src = raw.decode("utf-8")
                    tree = cache.get(raw)
                    if tree is None:
                        tree = ast.parse(src, filename=path)
                        cache.put(raw, tree)
                except (SyntaxError, UnicodeDecodeError, OSError) as e:
                    self.parse_errors.append((rel, str(e)))
                    continue
                info = ModuleInfo(name=modname, path=path, relpath=rel,
                                  tree=tree, lines=src.splitlines(),
                                  is_package=fn == "__init__.py")
                self._scan_suppressions(info)
                self._scan_imports(info)
                self.modules[modname] = info
        cache.save()

    def _modname(self, path: str) -> str:
        rel = os.path.relpath(path, os.path.dirname(self.root))
        rel = rel[:-3] if rel.endswith(".py") else rel
        parts = rel.split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def _scan_suppressions(self, info: ModuleInfo) -> None:
        for i, line in enumerate(info.lines, start=1):
            if "raylint" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            info.suppressions.append(Suppression(
                line=i, rules=rules, reason=m.group("reason"),
                comment_only=line.strip().startswith("#")))

    def _scan_imports(self, info: ModuleInfo) -> None:
        """name -> fully-qualified target ("pkg.mod" or "pkg.mod.sym")."""
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    info.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(info, node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    info.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}"

    def _resolve_from(self, info: ModuleInfo,
                      node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        parts = info.name.split(".")
        # "from . import x" in a plain module drops the module's own
        # leaf; in a package __init__ the single dot IS the package
        # (its dotted name already lacks the "__init__" leaf), so a
        # package strips one level fewer.  Each extra dot climbs one
        # more package either way.
        drop = node.level - (1 if info.is_package else 0)
        if drop > len(parts):
            return None
        anchor = parts[:-drop] if drop else list(parts)
        if node.module:
            anchor = anchor + node.module.split(".")
        return ".".join(anchor) if anchor else None

    # ----------------------------------------------------------- indexing
    def _index(self) -> None:
        for info in self.modules.values():
            self._index_module_locks(info)
            for node in info.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._index_class(info, node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._index_func(info, node, cls=None)

    def _is_factory(self, info: ModuleInfo, call: ast.AST,
                    names: Set[str]) -> bool:
        """``threading.Lock()`` / ``Lock()`` (imported) value?"""
        if not isinstance(call, ast.Call):
            return False
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in names and \
                isinstance(f.value, ast.Name) and \
                info.imports.get(f.value.id, f.value.id) == "threading":
            return True
        if isinstance(f, ast.Name) and f.id in names and \
                info.imports.get(f.id, "").startswith("threading."):
            return True
        return False

    def _index_module_locks(self, info: ModuleInfo) -> None:
        for node in info.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if self._is_factory(info, node.value, _LOCK_FACTORIES):
                    info.locks.add(name)
                elif self._is_factory(info, node.value, _COND_FACTORIES):
                    info.conds.add(name)

    def _index_class(self, info: ModuleInfo, node: ast.ClassDef) -> None:
        qn = f"{info.name}:{node.name}"
        ci = ClassInfo(qualname=qn, module=info.name, name=node.name,
                       node=node,
                       bases=[b.id for b in node.bases
                              if isinstance(b, ast.Name)])
        self.classes[qn] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self._index_func(info, item, cls=node.name)
                ci.methods[item.name] = fi.qualname
        # lock attributes: "self.X = threading.Lock()" anywhere in the
        # class body (usually __init__, but not only)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t = sub.targets[0]
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    if self._is_factory(info, sub.value, _LOCK_FACTORIES):
                        ci.lock_attrs.add(t.attr)
                    elif self._is_factory(info, sub.value,
                                          _COND_FACTORIES):
                        ci.cond_attrs.add(t.attr)
                        arg = (sub.value.args[0]
                               if sub.value.args else None)
                        if isinstance(arg, ast.Attribute) and \
                                isinstance(arg.value, ast.Name) and \
                                arg.value.id == "self":
                            ci.cond_alias[t.attr] = arg.attr

    def _index_func(self, info: ModuleInfo, node, cls: Optional[str],
                    prefix: str = "") -> FuncInfo:
        base = f"{cls}." if cls else ""
        qn = f"{info.name}:{prefix}{base}{node.name}"
        fi = FuncInfo(qualname=qn, module=info.name, cls=cls,
                      name=node.name, node=node, line=node.lineno)
        self.functions[qn] = fi
        self.by_name.setdefault(node.name, []).append(qn)
        # nested defs become their own nodes (resolved by local name)
        self._index_nested(info, node, cls,
                           prefix=f"{prefix}{base}{node.name}.")
        return fi

    def _index_nested(self, info: ModuleInfo, func_node, cls,
                      prefix) -> None:
        """Index the defs DIRECTLY nested in ``func_node``; each level
        recurses with its own prefix, so ``outer.a.helper`` and
        ``outer.b.helper`` never collide (a collision would silently
        drop the second body from every rule's scan)."""
        for sub in self._direct_child_defs(func_node):
            qn = f"{info.name}:{prefix}{sub.name}"
            if qn in self.functions:
                # same name re-bound within one scope (rare):
                # disambiguate by line rather than drop the body
                qn = f"{qn}@{sub.lineno}"
            fi = FuncInfo(qualname=qn, module=info.name, cls=cls,
                          name=sub.name, node=sub, line=sub.lineno)
            self.functions[qn] = fi
            self.by_name.setdefault(sub.name, []).append(qn)
            self._index_nested(info, sub, cls,
                               prefix=f"{prefix}{sub.name}.")

    @staticmethod
    def _direct_child_defs(func_node):
        """FunctionDefs nested in ``func_node`` without crossing
        another function boundary (does descend into if/try/with/
        loops and class bodies)."""
        out = []
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                out.append(node)
                continue
            if isinstance(node, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    # --------------------------------------------------------- call graph
    def _build_call_graph(self) -> None:
        for fi in list(self.functions.values()):
            edges: List[CallEdge] = []
            info = self.modules[fi.module]
            for node in self.walk_own(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._resolve_call_edge(info, fi, node)
                if hit is not None:
                    target, kind = hit
                    edges.append(CallEdge(target, node.lineno,
                                          call_desc(node), kind))
            self.call_edges[fi.qualname] = edges
            self.calls[fi.qualname] = [(e.target, e.line, e.via)
                                       for e in edges]

    def walk_own(self, func_node):
        """All nodes of a function body WITHOUT descending into nested
        function definitions (they execute elsewhere) or lambdas.
        Cached per node: every rule re-walks every function, and the
        traversal dominates the whole lint wall-clock otherwise."""
        cached = self._own_cache.get(id(func_node))
        if cached is not None:
            return cached
        out = []
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        self._own_cache[id(func_node)] = out
        return out

    def _resolve_call(self, info: ModuleInfo, fi: FuncInfo,
                      call: ast.Call) -> Optional[str]:
        hit = self._resolve_call_edge(info, fi, call)
        return hit[0] if hit is not None else None

    def _resolve_call_edge(self, info: ModuleInfo, fi: FuncInfo,
                           call: ast.Call
                           ) -> Optional[Tuple[str, str]]:
        """(callee qualname, edge kind) — see CallEdge for kinds."""
        key = (id(call), fi.qualname)
        if key in self._edge_cache:
            return self._edge_cache[key]
        out = self._resolve_call_edge_uncached(info, fi, call)
        self._edge_cache[key] = out
        return out

    def _resolve_call_edge_uncached(self, info: ModuleInfo,
                                    fi: FuncInfo, call: ast.Call
                                    ) -> Optional[Tuple[str, str]]:
        f = call.func
        if isinstance(f, ast.Name):
            return self._resolve_name_kind(info, fi, f.id)
        if isinstance(f, ast.Attribute):
            # self.method(...)
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and fi.cls is not None:
                qn = self._method_on(info.name, fi.cls, f.attr)
                if qn is not None:
                    return qn, "self"
            # module_alias.func(...)
            if isinstance(f.value, ast.Name):
                target = info.imports.get(f.value.id)
                if target in self.modules:
                    mod = self.modules[target]
                    qn = f"{mod.name}:{f.attr}"
                    if qn in self.functions:
                        return qn, "module"
            # unique-method fallback: exactly one project definition of
            # this name -> conservative (class-blind) edge
            cands = self.by_name.get(f.attr, ())
            if len(cands) == 1:
                return cands[0], "fallback"
        return None

    def _method_on(self, module: str, cls: str,
                   name: str) -> Optional[str]:
        """Method lookup on a class, following project-local bases."""
        seen: Set[str] = set()
        stack = [f"{module}:{cls}"]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            ci = self.classes.get(key)
            if ci is None:
                continue
            if name in ci.methods:
                return ci.methods[name]
            for base in ci.bases:
                # same module first, else any project class of the name
                if f"{ci.module}:{base}" in self.classes:
                    stack.append(f"{ci.module}:{base}")
                else:
                    stack.extend(k for k in self.classes
                                 if k.endswith(f":{base}"))
        return None

    def _resolve_name(self, info: ModuleInfo, fi: FuncInfo,
                      name: str) -> Optional[str]:
        hit = self._resolve_name_kind(info, fi, name)
        return hit[0] if hit is not None else None

    def _resolve_name_kind(self, info: ModuleInfo, fi: FuncInfo,
                           name: str) -> Optional[Tuple[str, str]]:
        # sibling nested function first (shares the enclosing prefix)
        prefix = fi.qualname.rsplit(".", 1)[0]
        for cand, kind in ((f"{prefix}.{name}", "local"),
                           (f"{fi.qualname}.{name}", "local"),
                           (f"{info.name}:{name}", "module")):
            if cand in self.functions:
                return cand, kind
        imported = info.imports.get(name)
        if imported:
            # imported function...
            mod, _, sym = imported.rpartition(".")
            qn = f"{mod}:{sym}"
            if qn in self.functions:
                return qn, "import"
            # ...or imported project class -> its __init__
            ci = self.classes.get(qn)
            if ci and "__init__" in ci.methods:
                return ci.methods["__init__"], "init"
        # class defined in this module -> __init__
        ci = self.classes.get(f"{info.name}:{name}")
        if ci and "__init__" in ci.methods:
            return ci.methods["__init__"], "init"
        return None

    # --------------------------------------------------------- utilities
    def lock_context(self, info: ModuleInfo, fi: FuncInfo,
                     expr: ast.AST) -> Optional[Tuple[str, bool]]:
        """(lock name, is_condition) when ``expr`` (a with-item) is a
        known lock/condition object, else None.  Falls back to a name
        heuristic (``*_lock`` / ``*mutex*`` / ``*_cond``) for locks
        passed in from elsewhere."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fi.cls is not None:
            ci = self.classes.get(f"{fi.module}:{fi.cls}")
            if ci is not None:
                if expr.attr in ci.lock_attrs:
                    return expr.attr, False
                if expr.attr in ci.cond_attrs:
                    return expr.attr, True
            return _lock_by_name(expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in info.locks:
                return expr.id, False
            if expr.id in info.conds:
                return expr.id, True
            return _lock_by_name(expr.id)
        return None


def _lock_by_name(name: str) -> Optional[Tuple[str, bool]]:
    low = name.lower()
    if low.endswith("_cond") or low.endswith("cond"):
        return name, True
    if low.endswith("lock") or "mutex" in low:
        return name, False
    return None


def call_desc(call: ast.Call) -> str:
    """Short printable form of a call target ("self.head.call")."""
    try:
        return ast.unparse(call.func)
    except Exception:
        return "<call>"


# --------------------------------------------------------------------------
# parse cache: content-hash-keyed ASTs
# --------------------------------------------------------------------------

class _ParseCache:
    """Content-hash-keyed AST memo, PROCESS-LOCAL by design.

    ``ast.parse`` dominates a cold model build, and the tier-1 lint
    gate builds the model repeatedly in one process (fixture corpora,
    the whole-package self-lint, the model unit tests): an unchanged
    file re-parses identically every time, so trees are memoized by
    ``sha1(file bytes)`` — an edit anywhere in a file misses only that
    file.  Sharing tree objects across ProjectModel instances is safe:
    nothing mutates them, and the per-model node caches key by id().

    Deliberately NOT persisted to disk: pickling ASTs was measured
    SLOWER to load than re-parsing (~1.6 s pickle.loads vs ~1.1 s
    ast.parse for the whole package on CPython 3.10 — generic
    attribute-by-attribute object reconstruction loses to the C
    parser), so a cross-process cache would be a pessimization
    wearing a cache's name.  ``RAY_TPU_RAYLINT_CACHE=0`` disables the
    memo (debugging, memory-constrained runs)."""

    _memo: Dict[str, ast.Module] = {}
    _MAX_ENTRIES = 4096  # ~40 MiB worst case; clear-all on overflow

    def __init__(self, enabled: bool):
        self._enabled = enabled

    @classmethod
    def open(cls, root: str) -> "_ParseCache":
        return cls(os.environ.get("RAY_TPU_RAYLINT_CACHE", "") != "0")

    @staticmethod
    def _key(raw: bytes) -> str:
        return hashlib.sha1(raw).hexdigest()

    def get(self, raw: bytes) -> Optional[ast.Module]:
        if not self._enabled:
            return None
        return self._memo.get(self._key(raw))

    def put(self, raw: bytes, tree: ast.Module) -> None:
        if not self._enabled:
            return
        if len(self._memo) >= self._MAX_ENTRIES:
            self._memo.clear()
        self._memo[self._key(raw)] = tree

    def save(self) -> None:
        pass  # process-local: nothing to flush


# --------------------------------------------------------------------------
# interprocedural lock-set analysis
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class LockToken:
    """Canonical lock identity.  ``key`` merges aliases (a
    ``Condition(self._lock)`` IS its lock for ordering); ``is_cond``
    remembers the syntactic shape for the wait rules; ``global_`` is
    False for bare-name locals/params whose identity can't be
    canonicalized across functions (they stay out of the global
    graph)."""
    key: str
    is_cond: bool
    global_: bool

    def short(self) -> str:
        mod, _, rest = self.key.partition(":")
        return f"{mod.rsplit('.', 1)[-1]}.{rest}"


@dataclass
class LockAcquire:
    token: LockToken
    line: int
    held: Tuple[LockToken, ...]    # locks already held at this site


@dataclass
class LockWait:
    token: LockToken               # the lock/condition being waited on
    line: int
    held: Tuple[LockToken, ...]
    timeouted: bool
    desc: str


@dataclass
class FuncLockFacts:
    acquires: List[LockAcquire] = field(default_factory=list)
    # (callee qualname, line, edge kind, held tokens at the call)
    calls: List[Tuple[str, int, str, Tuple[LockToken, ...]]] = \
        field(default_factory=list)
    waits: List[LockWait] = field(default_factory=list)


class LockAnalysis:
    """For every function: which locks may be HELD when it runs —
    locally (enclosing ``with`` regions) and interprocedurally (the
    union over callers, propagated to a fixpoint over the call graph's
    confident edges; the class-blind unique-name fallback edges are
    excluded so one guessed edge can't smear a lock set across the
    package).  From the per-function facts it assembles the global
    lock-acquisition-order graph: an edge A -> B for every site that
    acquires B while A may be held, each edge carrying witnesses
    (function, file, line, whether A came in through the entry set).
    Cycles in that graph are the ABBA deadlock candidates
    ``lock-order-inversion`` reports."""

    _PROPAGATE_KINDS = ("self", "local", "module", "import", "init")
    _MAX_WITNESSES = 3

    def __init__(self, model: ProjectModel):
        self.model = model
        self.facts: Dict[str, FuncLockFacts] = {}
        # fn qualname -> tokens possibly held on entry (strings = keys)
        self.entry: Dict[str, Set[str]] = {}
        # (fn, token key) -> (caller, line, caller_held_locally)
        self.entry_why: Dict[Tuple[str, str],
                             Tuple[str, int, bool]] = {}
        # (held key, acquired key) -> [(fn, relpath, line, via_entry)]
        self.edges: Dict[Tuple[str, str],
                         List[Tuple[str, str, int, bool]]] = {}
        self._token_cache: Dict[Tuple[str, str, str],
                                Optional[LockToken]] = {}
        for qn in sorted(model.functions):
            fi = model.functions[qn]
            info = model.modules[fi.module]
            self.facts[qn] = self._scan_func(info, fi)
        self._propagate()
        self._build_graph()

    # ------------------------------------------------- token resolution
    def _class_lock_owner(self, module: str, cls: str,
                          attr: str) -> Optional[Tuple[str, str, bool]]:
        """(owner class qualname, canonical attr, is_cond) for a
        ``self.<attr>`` lock/condition, following project-local bases
        and the Condition->lock alias chain."""
        seen: Set[str] = set()
        stack = [f"{module}:{cls}"]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            ci = self.model.classes.get(key)
            if ci is None:
                continue
            if attr in ci.cond_attrs:
                canon = attr
                hops = 0
                while canon in ci.cond_alias and hops < 4:
                    canon = ci.cond_alias[canon]
                    hops += 1
                return ci.qualname, canon, True
            if attr in ci.lock_attrs:
                return ci.qualname, attr, False
            for base in ci.bases:
                if f"{ci.module}:{base}" in self.model.classes:
                    stack.append(f"{ci.module}:{base}")
                else:
                    stack.extend(k for k in self.model.classes
                                 if k.endswith(f":{base}"))
        return None

    def token_for(self, info: ModuleInfo, fi: FuncInfo,
                  expr: ast.AST) -> Optional[LockToken]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fi.cls is not None:
            ck = (fi.module, fi.cls, expr.attr)
            if ck in self._token_cache:
                return self._token_cache[ck]
            owner = self._class_lock_owner(fi.module, fi.cls, expr.attr)
            if owner is not None:
                cls_qn, canon, is_cond = owner
                tok = LockToken(f"{cls_qn}.{canon}", is_cond, True)
            else:
                hit = _lock_by_name(expr.attr)
                tok = None
                if hit is not None:
                    # Heuristic self-attr: same class + attr is the
                    # same lock in practice, so it joins the graph.
                    tok = LockToken(f"{fi.module}:{fi.cls}.{expr.attr}",
                                    hit[1], True)
            self._token_cache[ck] = tok
            return tok
        if isinstance(expr, ast.Name):
            if expr.id in info.locks:
                return LockToken(f"{info.name}:{expr.id}", False, True)
            if expr.id in info.conds:
                return LockToken(f"{info.name}:{expr.id}", True, True)
            hit = _lock_by_name(expr.id)
            if hit is not None:
                # A local/parameter lock: real for THIS function's
                # waits, meaningless as a global identity.
                return LockToken(f"{fi.qualname}:{expr.id}",
                                 hit[1], False)
        return None

    # ----------------------------------------------------- local facts
    def _scan_func(self, info: ModuleInfo,
                   fi: FuncInfo) -> FuncLockFacts:
        # Fast path: no with-statements and no .wait() calls means no
        # acquisitions, no waits, and an empty held-set at every call
        # — take the calls straight from the prebuilt graph instead
        # of re-walking the body (the vast majority of functions).
        interesting = False
        for node in self.model.walk_own(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)) or (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"):
                interesting = True
                break
        if not interesting:
            return FuncLockFacts(calls=[
                (e.target, e.line, e.kind, ())
                for e in self.model.call_edges.get(fi.qualname, ())])
        facts = FuncLockFacts()
        self._scan_stmts(info, fi, fi.node.body, (), facts)
        return facts

    def _scan_stmts(self, info, fi, stmts, held, facts) -> None:
        for st in stmts:
            self._scan_node(info, fi, st, held, facts)

    def _scan_node(self, info, fi, node, held, facts) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                # the context expression evaluates BEFORE acquisition
                self._scan_node(info, fi, item.context_expr,
                                tuple(inner), facts)
                tok = self.token_for(info, fi, item.context_expr)
                if tok is not None:
                    facts.acquires.append(LockAcquire(
                        tok, node.lineno, tuple(inner)))
                    if tok.key not in {t.key for t in inner}:
                        inner.append(tok)
            self._scan_stmts(info, fi, node.body, tuple(inner), facts)
            return
        if isinstance(node, ast.Call):
            self._record_call(info, fi, node, held, facts)
        for child in ast.iter_child_nodes(node):
            self._scan_node(info, fi, child, held, facts)

    def _record_call(self, info, fi, call: ast.Call, held,
                     facts) -> None:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "wait":
            tok = self.token_for(info, fi, f.value)
            if tok is not None:
                timeouted = bool(call.args) or any(
                    kw.arg in ("timeout", "timeout_s")
                    for kw in call.keywords)
                facts.waits.append(LockWait(
                    tok, call.lineno, tuple(held), timeouted,
                    call_desc(call)))
        hit = self.model._resolve_call_edge(info, fi, call)
        if hit is not None:
            target, kind = hit
            facts.calls.append((target, call.lineno, kind,
                                tuple(t for t in held if t.global_)))

    # ----------------------------------------------------- propagation
    def _propagate(self) -> None:
        """Fixpoint: entry(callee) ⊇ entry(caller) ∪ held-at-call for
        every confident edge.  Deterministic: functions and tokens are
        visited sorted, and the first witness for a (fn, token) entry
        is kept — chains render identically across runs and
        interpreters."""
        entry = self.entry
        for qn in self.facts:
            entry.setdefault(qn, set())
        changed = True
        while changed:
            changed = False
            for qn in sorted(self.facts):
                base = entry[qn]
                for target, line, kind, held in self.facts[qn].calls:
                    if kind not in self._PROPAGATE_KINDS:
                        continue
                    if target == qn or target not in entry:
                        continue
                    held_keys = {t.key for t in held}
                    contrib = base | held_keys
                    fresh = contrib - entry[target]
                    if not fresh:
                        continue
                    entry[target] |= fresh
                    for tkey in sorted(fresh):
                        self.entry_why.setdefault(
                            (target, tkey),
                            (qn, line, tkey in held_keys))
                    changed = True

    def chain(self, qn: str, token_key: str) -> List[str]:
        """Printable caller hops explaining how ``qn`` may run with
        ``token_key`` held: root (the function that actually acquires
        it) first.  Line-number-free so finding messages stay
        baseline-stable."""
        hops = [qn]
        seen = {qn}
        cur = qn
        while True:
            why = self.entry_why.get((cur, token_key))
            if why is None:
                break
            caller, _line, local = why
            if caller in seen:
                break
            hops.append(caller)
            seen.add(caller)
            cur = caller
            if local:
                break
        return [_short_fn(h) for h in reversed(hops)]

    # ----------------------------------------------------------- graph
    def _build_graph(self) -> None:
        for qn in sorted(self.facts):
            entry_keys = sorted(self.entry.get(qn, ()))
            fi = self.model.functions[qn]
            rel = self.model.modules[fi.module].relpath
            for acq in self.facts[qn].acquires:
                if not acq.token.global_:
                    continue
                local_keys = {t.key for t in acq.held if t.global_}
                for lkey in sorted(set(entry_keys) | local_keys):
                    if lkey == acq.token.key:
                        continue
                    wl = self.edges.setdefault(
                        (lkey, acq.token.key), [])
                    if len(wl) < self._MAX_WITNESSES:
                        wl.append((qn, rel, acq.line,
                                   lkey not in local_keys))

    def cycles(self, max_cycles: int = 64) -> List[List[str]]:
        """Simple cycles in the lock-order graph, each a token list
        ``[t0, .., tk]`` meaning t0->t1->..->tk->t0.  Deterministic:
        SCCs found over sorted adjacency, one shortest cycle per
        in-SCC edge, deduped by node set.  Self-loops (reentrant
        RLock) are not cycles."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            if a == b:
                continue
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for k in adj:
            adj[k] = sorted(set(adj[k]))
        out: List[List[str]] = []
        seen_sets: Set[frozenset] = set()
        for scc in _tarjan_sccs(adj):
            if len(scc) < 2:
                continue
            nodes = set(scc)
            for a in sorted(nodes):
                for b in adj[a]:
                    if b not in nodes:
                        continue
                    back = _shortest_path(adj, b, a, nodes)
                    if back is None:
                        continue
                    cyc = [a] + back[:-1]
                    key = frozenset(cyc)
                    if key in seen_sets:
                        continue
                    seen_sets.add(key)
                    out.append(cyc)
                    if len(out) >= max_cycles:
                        return out
        return out

    # ------------------------------------------------------------ dumps
    def to_json(self) -> Dict:
        """The global lock-order graph, offline-inspection shape
        (``ray_tpu lint --lock-graph json``)."""
        nodes = sorted({k for e in self.edges for k in e})
        return {
            "nodes": nodes,
            "edges": [{
                "from": a, "to": b,
                "witnesses": [{"function": fn, "path": rel,
                               "line": line, "via_entry": ve}
                              for fn, rel, line, ve in wits],
            } for (a, b), wits in sorted(self.edges.items())],
            "cycles": self.cycles(),
        }

    def to_dot(self) -> str:
        cyc_nodes = {t for cyc in self.cycles() for t in cyc}
        lines = ["digraph lock_order {",
                 '  rankdir=LR; node [shape=box, fontsize=10];']
        for tok in sorted({k for e in self.edges for k in e}):
            style = ', color=red, penwidth=2' if tok in cyc_nodes \
                else ''
            lines.append(f'  "{tok}" [label="{_short_key(tok)}"'
                         f'{style}];')
        for (a, b), wits in sorted(self.edges.items()):
            fn, rel, line, _ve = wits[0]
            lines.append(f'  "{a}" -> "{b}" '
                         f'[label="{_short_fn(fn)}:{line}", '
                         f'fontsize=8];')
        lines.append("}")
        return "\n".join(lines) + "\n"


def _short_fn(qualname: str) -> str:
    """'pkg.mod:Cls.meth' -> 'mod:Cls.meth' (message-stable)."""
    mod, _, rest = qualname.partition(":")
    return f"{mod.rsplit('.', 1)[-1]}:{rest}"


def _short_key(token_key: str) -> str:
    mod, _, rest = token_key.partition(":")
    return f"{mod.rsplit('.', 1)[-1]}.{rest}"


def _tarjan_sccs(adj: Dict[str, List[str]]) -> List[List[str]]:
    """Iterative Tarjan over sorted nodes/neighbors (deterministic,
    recursion-free — lock graphs are small but cycles in them are
    exactly when a recursive walk would go deep)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(adj[nxt])))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(sorted(scc))
    return sccs


def _shortest_path(adj: Dict[str, List[str]], src: str, dst: str,
                   allowed: Set[str]) -> Optional[List[str]]:
    """BFS path src..dst (inclusive) within ``allowed``; sorted
    neighbor order keeps the chosen path deterministic."""
    if src == dst:
        return [src]
    prev: Dict[str, str] = {src: src}
    frontier = [src]
    while frontier:
        nxt_frontier = []
        for node in frontier:
            for nxt in adj.get(node, ()):
                if nxt not in allowed or nxt in prev:
                    continue
                prev[nxt] = node
                if nxt == dst:
                    path = [dst]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                nxt_frontier.append(nxt)
        frontier = nxt_frontier
    return None
