"""The raylint project model: ONE parse of the whole package.

Every rule runs against this shared index instead of re-walking files:

- module index: dotted module name -> parsed AST + source lines
- function table: qualified name ("pkg.mod:Cls.meth") -> FuncInfo
- class table: lock/condition attributes (assignments of
  ``threading.Lock/RLock/Condition``), method sets, base names
- call graph: conservative name-based resolution (self-methods,
  module-local functions, imported symbols, project classes ->
  ``__init__``, plus a unique-method-name fallback for cross-class
  edges) — enough to chase ``blocking-under-lock`` transitively
- suppressions: ``# raylint: disable=<rule>[,<rule>] -- reason``
  parsed out of the raw source (AST drops comments)

The model is deliberately approximate where Python is dynamic: rules
prefer a small number of explainable false positives (silenced with a
reasoned ``disable``) over silent false negatives in the invariants
this framework actually depends on.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# disable comment syntax: "raylint: disable=<rules> -- <why>"
_SUPPRESS_RE = re.compile(
    r"#\s*raylint:\s*disable=([a-zA-Z0-9_,\- ]+?)"
    r"(?:\s*--\s*(?P<reason>\S.*))?\s*$")

_LOCK_FACTORIES = {"Lock", "RLock"}
_COND_FACTORIES = {"Condition"}


@dataclass
class Suppression:
    line: int
    rules: Set[str]
    reason: Optional[str]
    comment_only: bool  # whole line is the comment -> guards line+1


@dataclass
class ModuleInfo:
    name: str                      # dotted ("ray_tpu.cluster.head")
    path: str                      # absolute
    relpath: str                   # project-root relative
    tree: ast.Module
    lines: List[str]
    is_package: bool = False       # an __init__.py (relative imports
    #                                anchor at the package ITSELF)
    suppressions: List[Suppression] = field(default_factory=list)
    imports: Dict[str, str] = field(default_factory=dict)
    # module-level names bound to threading.Lock()/RLock()/Condition()
    locks: Set[str] = field(default_factory=set)
    conds: Set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    qualname: str                  # "pkg.mod:Cls"
    module: str
    name: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, str] = field(default_factory=dict)  # name->func qn
    lock_attrs: Set[str] = field(default_factory=set)
    cond_attrs: Set[str] = field(default_factory=set)
    # cond attr -> the lock attr it WRAPS ("self._cond =
    # threading.Condition(self._lock)"): the condition IS that lock
    # for ordering purposes — acquiring one while holding the other
    # is reentrant, not an inversion.
    cond_alias: Dict[str, str] = field(default_factory=dict)


@dataclass
class CallEdge:
    """One call-graph edge with its resolution confidence.  ``kind``:
    "self" (self.method), "local" (sibling/nested def), "module"
    (module-local function or alias.func into a project module),
    "import" (imported project symbol), "init" (class -> __init__),
    "fallback" (unique-method-name guess — class-blind, the edge the
    lock-set propagation must NOT trust)."""
    target: str
    line: int
    via: str
    kind: str


@dataclass
class FuncInfo:
    qualname: str                  # "pkg.mod:Cls.meth" / "pkg.mod:fn"
    module: str
    cls: Optional[str]             # enclosing class simple name
    name: str
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    line: int


class ProjectModel:
    """Parse ``root`` (a package directory) once and index it."""

    def __init__(self, root: str, package: Optional[str] = None):
        self.root = os.path.abspath(root)
        self.project_dir = os.path.dirname(self.root) or "."
        self.package = package or os.path.basename(self.root.rstrip("/"))
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        # bare function/method name -> qualnames defining it
        self.by_name: Dict[str, List[str]] = {}
        # call graph: func qualname -> [(callee qualname, line, via)]
        # (legacy 3-tuple view; call_edges carries the resolution kind)
        self.calls: Dict[str, List[Tuple[str, int, str]]] = {}
        self.call_edges: Dict[str, List[CallEdge]] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        self._own_cache: Dict[int, List[ast.AST]] = {}
        # (call-node id, enclosing fn qualname) -> resolved
        # (target, kind) | None.  Resolution (inheritance walks,
        # import chasing) is re-requested for the same Call node by
        # the call-graph build, the lock-set scan, the raise
        # inference, and the try indexing — memoize it.  Node ids
        # stay valid for the model's lifetime (ModuleInfo pins every
        # tree); the qualname qualifier matters because the parse
        # memo SHARES one AST between byte-identical files, so the
        # same node resolves under different modules' import/class
        # contexts.
        self._edge_cache: Dict[Tuple[int, str],
                               Optional[Tuple[str, str]]] = {}
        self._locks: Optional[LockAnalysis] = None
        self._flow: Optional[DeviceFlow] = None
        self._load()
        self._index()
        self._build_call_graph()

    def lock_analysis(self) -> "LockAnalysis":
        """The interprocedural lock-set model, built once on demand
        (the lock-order and wait rules share it, and the CLI dumps
        its graph)."""
        if self._locks is None:
            self._locks = LockAnalysis(self)
        return self._locks

    def device_flow(self) -> "DeviceFlow":
        """The traced-value (device-plane) dataflow model, built once
        on demand — the host-device-sync / recompile-hazard /
        missing-donation rules all read it."""
        if self._flow is None:
            self._flow = DeviceFlow(self)
        return self._flow

    # ------------------------------------------------------------ loading
    def _load(self) -> None:
        cache = _ParseCache.open(self.project_dir)
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.project_dir)
                modname = self._modname(path)
                try:
                    with open(path, "rb") as f:
                        raw = f.read()
                    src = raw.decode("utf-8")
                    tree = cache.get(raw)
                    if tree is None:
                        tree = ast.parse(src, filename=path)
                        cache.put(raw, tree)
                except (SyntaxError, UnicodeDecodeError, OSError) as e:
                    self.parse_errors.append((rel, str(e)))
                    continue
                info = ModuleInfo(name=modname, path=path, relpath=rel,
                                  tree=tree, lines=src.splitlines(),
                                  is_package=fn == "__init__.py")
                self._scan_suppressions(info)
                self._scan_imports(info)
                self.modules[modname] = info
        cache.save()

    def _modname(self, path: str) -> str:
        rel = os.path.relpath(path, os.path.dirname(self.root))
        rel = rel[:-3] if rel.endswith(".py") else rel
        parts = rel.split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def _scan_suppressions(self, info: ModuleInfo) -> None:
        for i, line in enumerate(info.lines, start=1):
            if "raylint" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            info.suppressions.append(Suppression(
                line=i, rules=rules, reason=m.group("reason"),
                comment_only=line.strip().startswith("#")))

    def _scan_imports(self, info: ModuleInfo) -> None:
        """name -> fully-qualified target ("pkg.mod" or "pkg.mod.sym")."""
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    info.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(info, node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    info.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}"

    def _resolve_from(self, info: ModuleInfo,
                      node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        parts = info.name.split(".")
        # "from . import x" in a plain module drops the module's own
        # leaf; in a package __init__ the single dot IS the package
        # (its dotted name already lacks the "__init__" leaf), so a
        # package strips one level fewer.  Each extra dot climbs one
        # more package either way.
        drop = node.level - (1 if info.is_package else 0)
        if drop > len(parts):
            return None
        anchor = parts[:-drop] if drop else list(parts)
        if node.module:
            anchor = anchor + node.module.split(".")
        return ".".join(anchor) if anchor else None

    # ----------------------------------------------------------- indexing
    def _index(self) -> None:
        for info in self.modules.values():
            self._index_module_locks(info)
            for node in info.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._index_class(info, node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._index_func(info, node, cls=None)

    def _is_factory(self, info: ModuleInfo, call: ast.AST,
                    names: Set[str]) -> bool:
        """``threading.Lock()`` / ``Lock()`` (imported) value?"""
        if not isinstance(call, ast.Call):
            return False
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in names and \
                isinstance(f.value, ast.Name) and \
                info.imports.get(f.value.id, f.value.id) == "threading":
            return True
        if isinstance(f, ast.Name) and f.id in names and \
                info.imports.get(f.id, "").startswith("threading."):
            return True
        return False

    def _index_module_locks(self, info: ModuleInfo) -> None:
        for node in info.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if self._is_factory(info, node.value, _LOCK_FACTORIES):
                    info.locks.add(name)
                elif self._is_factory(info, node.value, _COND_FACTORIES):
                    info.conds.add(name)

    def _index_class(self, info: ModuleInfo, node: ast.ClassDef) -> None:
        qn = f"{info.name}:{node.name}"
        ci = ClassInfo(qualname=qn, module=info.name, name=node.name,
                       node=node,
                       bases=[b.id for b in node.bases
                              if isinstance(b, ast.Name)])
        self.classes[qn] = ci
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self._index_func(info, item, cls=node.name)
                ci.methods[item.name] = fi.qualname
        # lock attributes: "self.X = threading.Lock()" anywhere in the
        # class body (usually __init__, but not only)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t = sub.targets[0]
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    if self._is_factory(info, sub.value, _LOCK_FACTORIES):
                        ci.lock_attrs.add(t.attr)
                    elif self._is_factory(info, sub.value,
                                          _COND_FACTORIES):
                        ci.cond_attrs.add(t.attr)
                        arg = (sub.value.args[0]
                               if sub.value.args else None)
                        if isinstance(arg, ast.Attribute) and \
                                isinstance(arg.value, ast.Name) and \
                                arg.value.id == "self":
                            ci.cond_alias[t.attr] = arg.attr

    def _index_func(self, info: ModuleInfo, node, cls: Optional[str],
                    prefix: str = "") -> FuncInfo:
        base = f"{cls}." if cls else ""
        qn = f"{info.name}:{prefix}{base}{node.name}"
        fi = FuncInfo(qualname=qn, module=info.name, cls=cls,
                      name=node.name, node=node, line=node.lineno)
        self.functions[qn] = fi
        self.by_name.setdefault(node.name, []).append(qn)
        # nested defs become their own nodes (resolved by local name)
        self._index_nested(info, node, cls,
                           prefix=f"{prefix}{base}{node.name}.")
        return fi

    def _index_nested(self, info: ModuleInfo, func_node, cls,
                      prefix) -> None:
        """Index the defs DIRECTLY nested in ``func_node``; each level
        recurses with its own prefix, so ``outer.a.helper`` and
        ``outer.b.helper`` never collide (a collision would silently
        drop the second body from every rule's scan)."""
        for sub in self._direct_child_defs(func_node):
            qn = f"{info.name}:{prefix}{sub.name}"
            if qn in self.functions:
                # same name re-bound within one scope (rare):
                # disambiguate by line rather than drop the body
                qn = f"{qn}@{sub.lineno}"
            fi = FuncInfo(qualname=qn, module=info.name, cls=cls,
                          name=sub.name, node=sub, line=sub.lineno)
            self.functions[qn] = fi
            self.by_name.setdefault(sub.name, []).append(qn)
            self._index_nested(info, sub, cls,
                               prefix=f"{prefix}{sub.name}.")

    @staticmethod
    def _direct_child_defs(func_node):
        """FunctionDefs nested in ``func_node`` without crossing
        another function boundary (does descend into if/try/with/
        loops and class bodies)."""
        out = []
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                out.append(node)
                continue
            if isinstance(node, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(node))
        return out

    # --------------------------------------------------------- call graph
    def _build_call_graph(self) -> None:
        for fi in list(self.functions.values()):
            edges: List[CallEdge] = []
            info = self.modules[fi.module]
            for node in self.walk_own(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._resolve_call_edge(info, fi, node)
                if hit is not None:
                    target, kind = hit
                    edges.append(CallEdge(target, node.lineno,
                                          call_desc(node), kind))
            self.call_edges[fi.qualname] = edges
            self.calls[fi.qualname] = [(e.target, e.line, e.via)
                                       for e in edges]

    def walk_own(self, func_node):
        """All nodes of a function body WITHOUT descending into nested
        function definitions (they execute elsewhere) or lambdas.
        Cached per node: every rule re-walks every function, and the
        traversal dominates the whole lint wall-clock otherwise."""
        cached = self._own_cache.get(id(func_node))
        if cached is not None:
            return cached
        out = []
        stack = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        self._own_cache[id(func_node)] = out
        return out

    def _resolve_call(self, info: ModuleInfo, fi: FuncInfo,
                      call: ast.Call) -> Optional[str]:
        hit = self._resolve_call_edge(info, fi, call)
        return hit[0] if hit is not None else None

    def _resolve_call_edge(self, info: ModuleInfo, fi: FuncInfo,
                           call: ast.Call
                           ) -> Optional[Tuple[str, str]]:
        """(callee qualname, edge kind) — see CallEdge for kinds."""
        key = (id(call), fi.qualname)
        if key in self._edge_cache:
            return self._edge_cache[key]
        out = self._resolve_call_edge_uncached(info, fi, call)
        self._edge_cache[key] = out
        return out

    def _resolve_call_edge_uncached(self, info: ModuleInfo,
                                    fi: FuncInfo, call: ast.Call
                                    ) -> Optional[Tuple[str, str]]:
        f = call.func
        if isinstance(f, ast.Name):
            return self._resolve_name_kind(info, fi, f.id)
        if isinstance(f, ast.Attribute):
            # self.method(...)
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and fi.cls is not None:
                qn = self._method_on(info.name, fi.cls, f.attr)
                if qn is not None:
                    return qn, "self"
            # module_alias.func(...)
            if isinstance(f.value, ast.Name):
                target = info.imports.get(f.value.id)
                if target in self.modules:
                    mod = self.modules[target]
                    qn = f"{mod.name}:{f.attr}"
                    if qn in self.functions:
                        return qn, "module"
            # unique-method fallback: exactly one project definition of
            # this name -> conservative (class-blind) edge
            cands = self.by_name.get(f.attr, ())
            if len(cands) == 1:
                return cands[0], "fallback"
        return None

    def _method_on(self, module: str, cls: str,
                   name: str) -> Optional[str]:
        """Method lookup on a class, following project-local bases."""
        seen: Set[str] = set()
        stack = [f"{module}:{cls}"]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            ci = self.classes.get(key)
            if ci is None:
                continue
            if name in ci.methods:
                return ci.methods[name]
            for base in ci.bases:
                # same module first, else any project class of the name
                if f"{ci.module}:{base}" in self.classes:
                    stack.append(f"{ci.module}:{base}")
                else:
                    stack.extend(k for k in self.classes
                                 if k.endswith(f":{base}"))
        return None

    def _resolve_name(self, info: ModuleInfo, fi: FuncInfo,
                      name: str) -> Optional[str]:
        hit = self._resolve_name_kind(info, fi, name)
        return hit[0] if hit is not None else None

    def _resolve_name_kind(self, info: ModuleInfo, fi: FuncInfo,
                           name: str) -> Optional[Tuple[str, str]]:
        # sibling nested function first (shares the enclosing prefix)
        prefix = fi.qualname.rsplit(".", 1)[0]
        for cand, kind in ((f"{prefix}.{name}", "local"),
                           (f"{fi.qualname}.{name}", "local"),
                           (f"{info.name}:{name}", "module")):
            if cand in self.functions:
                return cand, kind
        imported = info.imports.get(name)
        if imported:
            # imported function...
            mod, _, sym = imported.rpartition(".")
            qn = f"{mod}:{sym}"
            if qn in self.functions:
                return qn, "import"
            # ...or imported project class -> its __init__
            ci = self.classes.get(qn)
            if ci and "__init__" in ci.methods:
                return ci.methods["__init__"], "init"
        # class defined in this module -> __init__
        ci = self.classes.get(f"{info.name}:{name}")
        if ci and "__init__" in ci.methods:
            return ci.methods["__init__"], "init"
        return None

    # --------------------------------------------------------- utilities
    def lock_context(self, info: ModuleInfo, fi: FuncInfo,
                     expr: ast.AST) -> Optional[Tuple[str, bool]]:
        """(lock name, is_condition) when ``expr`` (a with-item) is a
        known lock/condition object, else None.  Falls back to a name
        heuristic (``*_lock`` / ``*mutex*`` / ``*_cond``) for locks
        passed in from elsewhere."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fi.cls is not None:
            ci = self.classes.get(f"{fi.module}:{fi.cls}")
            if ci is not None:
                if expr.attr in ci.lock_attrs:
                    return expr.attr, False
                if expr.attr in ci.cond_attrs:
                    return expr.attr, True
            return _lock_by_name(expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in info.locks:
                return expr.id, False
            if expr.id in info.conds:
                return expr.id, True
            return _lock_by_name(expr.id)
        return None


def _lock_by_name(name: str) -> Optional[Tuple[str, bool]]:
    low = name.lower()
    if low.endswith("_cond") or low.endswith("cond"):
        return name, True
    if low.endswith("lock") or "mutex" in low:
        return name, False
    return None


def call_desc(call: ast.Call) -> str:
    """Short printable form of a call target ("self.head.call")."""
    try:
        return ast.unparse(call.func)
    except Exception:
        return "<call>"


# --------------------------------------------------------------------------
# parse cache: content-hash-keyed ASTs
# --------------------------------------------------------------------------

class _ParseCache:
    """Content-hash-keyed AST memo, PROCESS-LOCAL by design.

    ``ast.parse`` dominates a cold model build, and the tier-1 lint
    gate builds the model repeatedly in one process (fixture corpora,
    the whole-package self-lint, the model unit tests): an unchanged
    file re-parses identically every time, so trees are memoized by
    ``sha1(file bytes)`` — an edit anywhere in a file misses only that
    file.  Sharing tree objects across ProjectModel instances is safe:
    nothing mutates them, and the per-model node caches key by id().

    Deliberately NOT persisted to disk: pickling ASTs was measured
    SLOWER to load than re-parsing (~1.6 s pickle.loads vs ~1.1 s
    ast.parse for the whole package on CPython 3.10 — generic
    attribute-by-attribute object reconstruction loses to the C
    parser), so a cross-process cache would be a pessimization
    wearing a cache's name.  ``RAY_TPU_RAYLINT_CACHE=0`` disables the
    memo (debugging, memory-constrained runs)."""

    _memo: Dict[str, ast.Module] = {}
    _MAX_ENTRIES = 4096  # ~40 MiB worst case; clear-all on overflow
    # Process-lifetime hit/miss counters: bench.py's raylint phase
    # reports the hit rate so the memo's payoff is tracked across PRs.
    _hits = 0
    _misses = 0

    def __init__(self, enabled: bool):
        self._enabled = enabled

    @classmethod
    def open(cls, root: str) -> "_ParseCache":
        return cls(os.environ.get("RAY_TPU_RAYLINT_CACHE", "") != "0")

    @classmethod
    def stats(cls) -> Dict[str, int]:
        return {"hits": cls._hits, "misses": cls._misses}

    @classmethod
    def reset_stats(cls) -> None:
        cls._hits = 0
        cls._misses = 0

    @staticmethod
    def _key(raw: bytes) -> str:
        return hashlib.sha1(raw).hexdigest()

    def get(self, raw: bytes) -> Optional[ast.Module]:
        if not self._enabled:
            return None
        tree = self._memo.get(self._key(raw))
        if tree is None:
            _ParseCache._misses += 1
        else:
            _ParseCache._hits += 1
        return tree

    def put(self, raw: bytes, tree: ast.Module) -> None:
        if not self._enabled:
            return
        if len(self._memo) >= self._MAX_ENTRIES:
            self._memo.clear()
        self._memo[self._key(raw)] = tree

    def save(self) -> None:
        pass  # process-local: nothing to flush


# --------------------------------------------------------------------------
# interprocedural lock-set analysis
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class LockToken:
    """Canonical lock identity.  ``key`` merges aliases (a
    ``Condition(self._lock)`` IS its lock for ordering); ``is_cond``
    remembers the syntactic shape for the wait rules; ``global_`` is
    False for bare-name locals/params whose identity can't be
    canonicalized across functions (they stay out of the global
    graph)."""
    key: str
    is_cond: bool
    global_: bool

    def short(self) -> str:
        mod, _, rest = self.key.partition(":")
        return f"{mod.rsplit('.', 1)[-1]}.{rest}"


@dataclass
class LockAcquire:
    token: LockToken
    line: int
    held: Tuple[LockToken, ...]    # locks already held at this site


@dataclass
class LockWait:
    token: LockToken               # the lock/condition being waited on
    line: int
    held: Tuple[LockToken, ...]
    timeouted: bool
    desc: str


@dataclass
class FuncLockFacts:
    acquires: List[LockAcquire] = field(default_factory=list)
    # (callee qualname, line, edge kind, held tokens at the call)
    calls: List[Tuple[str, int, str, Tuple[LockToken, ...]]] = \
        field(default_factory=list)
    waits: List[LockWait] = field(default_factory=list)


class LockAnalysis:
    """For every function: which locks may be HELD when it runs —
    locally (enclosing ``with`` regions) and interprocedurally (the
    union over callers, propagated to a fixpoint over the call graph's
    confident edges; the class-blind unique-name fallback edges are
    excluded so one guessed edge can't smear a lock set across the
    package).  From the per-function facts it assembles the global
    lock-acquisition-order graph: an edge A -> B for every site that
    acquires B while A may be held, each edge carrying witnesses
    (function, file, line, whether A came in through the entry set).
    Cycles in that graph are the ABBA deadlock candidates
    ``lock-order-inversion`` reports."""

    _PROPAGATE_KINDS = ("self", "local", "module", "import", "init")
    _MAX_WITNESSES = 3

    def __init__(self, model: ProjectModel):
        self.model = model
        self.facts: Dict[str, FuncLockFacts] = {}
        # fn qualname -> tokens possibly held on entry (strings = keys)
        self.entry: Dict[str, Set[str]] = {}
        # (fn, token key) -> (caller, line, caller_held_locally)
        self.entry_why: Dict[Tuple[str, str],
                             Tuple[str, int, bool]] = {}
        # (held key, acquired key) -> [(fn, relpath, line, via_entry)]
        self.edges: Dict[Tuple[str, str],
                         List[Tuple[str, str, int, bool]]] = {}
        self._token_cache: Dict[Tuple[str, str, str],
                                Optional[LockToken]] = {}
        for qn in sorted(model.functions):
            fi = model.functions[qn]
            info = model.modules[fi.module]
            self.facts[qn] = self._scan_func(info, fi)
        self._propagate()
        self._build_graph()

    # ------------------------------------------------- token resolution
    def _class_lock_owner(self, module: str, cls: str,
                          attr: str) -> Optional[Tuple[str, str, bool]]:
        """(owner class qualname, canonical attr, is_cond) for a
        ``self.<attr>`` lock/condition, following project-local bases
        and the Condition->lock alias chain."""
        seen: Set[str] = set()
        stack = [f"{module}:{cls}"]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            ci = self.model.classes.get(key)
            if ci is None:
                continue
            if attr in ci.cond_attrs:
                canon = attr
                hops = 0
                while canon in ci.cond_alias and hops < 4:
                    canon = ci.cond_alias[canon]
                    hops += 1
                return ci.qualname, canon, True
            if attr in ci.lock_attrs:
                return ci.qualname, attr, False
            for base in ci.bases:
                if f"{ci.module}:{base}" in self.model.classes:
                    stack.append(f"{ci.module}:{base}")
                else:
                    stack.extend(k for k in self.model.classes
                                 if k.endswith(f":{base}"))
        return None

    def token_for(self, info: ModuleInfo, fi: FuncInfo,
                  expr: ast.AST) -> Optional[LockToken]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fi.cls is not None:
            ck = (fi.module, fi.cls, expr.attr)
            if ck in self._token_cache:
                return self._token_cache[ck]
            owner = self._class_lock_owner(fi.module, fi.cls, expr.attr)
            if owner is not None:
                cls_qn, canon, is_cond = owner
                tok = LockToken(f"{cls_qn}.{canon}", is_cond, True)
            else:
                hit = _lock_by_name(expr.attr)
                tok = None
                if hit is not None:
                    # Heuristic self-attr: same class + attr is the
                    # same lock in practice, so it joins the graph.
                    tok = LockToken(f"{fi.module}:{fi.cls}.{expr.attr}",
                                    hit[1], True)
            self._token_cache[ck] = tok
            return tok
        if isinstance(expr, ast.Name):
            if expr.id in info.locks:
                return LockToken(f"{info.name}:{expr.id}", False, True)
            if expr.id in info.conds:
                return LockToken(f"{info.name}:{expr.id}", True, True)
            hit = _lock_by_name(expr.id)
            if hit is not None:
                # A local/parameter lock: real for THIS function's
                # waits, meaningless as a global identity.
                return LockToken(f"{fi.qualname}:{expr.id}",
                                 hit[1], False)
        return None

    # ----------------------------------------------------- local facts
    def _scan_func(self, info: ModuleInfo,
                   fi: FuncInfo) -> FuncLockFacts:
        # Fast path: no with-statements and no .wait() calls means no
        # acquisitions, no waits, and an empty held-set at every call
        # — take the calls straight from the prebuilt graph instead
        # of re-walking the body (the vast majority of functions).
        interesting = False
        for node in self.model.walk_own(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)) or (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"):
                interesting = True
                break
        if not interesting:
            return FuncLockFacts(calls=[
                (e.target, e.line, e.kind, ())
                for e in self.model.call_edges.get(fi.qualname, ())])
        facts = FuncLockFacts()
        self._scan_stmts(info, fi, fi.node.body, (), facts)
        return facts

    def _scan_stmts(self, info, fi, stmts, held, facts) -> None:
        for st in stmts:
            self._scan_node(info, fi, st, held, facts)

    def _scan_node(self, info, fi, node, held, facts) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                # the context expression evaluates BEFORE acquisition
                self._scan_node(info, fi, item.context_expr,
                                tuple(inner), facts)
                tok = self.token_for(info, fi, item.context_expr)
                if tok is not None:
                    facts.acquires.append(LockAcquire(
                        tok, node.lineno, tuple(inner)))
                    if tok.key not in {t.key for t in inner}:
                        inner.append(tok)
            self._scan_stmts(info, fi, node.body, tuple(inner), facts)
            return
        if isinstance(node, ast.Call):
            self._record_call(info, fi, node, held, facts)
        for child in ast.iter_child_nodes(node):
            self._scan_node(info, fi, child, held, facts)

    def _record_call(self, info, fi, call: ast.Call, held,
                     facts) -> None:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "wait":
            tok = self.token_for(info, fi, f.value)
            if tok is not None:
                timeouted = bool(call.args) or any(
                    kw.arg in ("timeout", "timeout_s")
                    for kw in call.keywords)
                facts.waits.append(LockWait(
                    tok, call.lineno, tuple(held), timeouted,
                    call_desc(call)))
        hit = self.model._resolve_call_edge(info, fi, call)
        if hit is not None:
            target, kind = hit
            facts.calls.append((target, call.lineno, kind,
                                tuple(t for t in held if t.global_)))

    # ----------------------------------------------------- propagation
    def _propagate(self) -> None:
        """Fixpoint: entry(callee) ⊇ entry(caller) ∪ held-at-call for
        every confident edge.  Deterministic: functions and tokens are
        visited sorted, and the first witness for a (fn, token) entry
        is kept — chains render identically across runs and
        interpreters."""
        entry = self.entry
        for qn in self.facts:
            entry.setdefault(qn, set())
        changed = True
        while changed:
            changed = False
            for qn in sorted(self.facts):
                base = entry[qn]
                for target, line, kind, held in self.facts[qn].calls:
                    if kind not in self._PROPAGATE_KINDS:
                        continue
                    if target == qn or target not in entry:
                        continue
                    held_keys = {t.key for t in held}
                    contrib = base | held_keys
                    fresh = contrib - entry[target]
                    if not fresh:
                        continue
                    entry[target] |= fresh
                    for tkey in sorted(fresh):
                        self.entry_why.setdefault(
                            (target, tkey),
                            (qn, line, tkey in held_keys))
                    changed = True

    def chain(self, qn: str, token_key: str) -> List[str]:
        """Printable caller hops explaining how ``qn`` may run with
        ``token_key`` held: root (the function that actually acquires
        it) first.  Line-number-free so finding messages stay
        baseline-stable."""
        hops = [qn]
        seen = {qn}
        cur = qn
        while True:
            why = self.entry_why.get((cur, token_key))
            if why is None:
                break
            caller, _line, local = why
            if caller in seen:
                break
            hops.append(caller)
            seen.add(caller)
            cur = caller
            if local:
                break
        return [_short_fn(h) for h in reversed(hops)]

    # ----------------------------------------------------------- graph
    def _build_graph(self) -> None:
        for qn in sorted(self.facts):
            entry_keys = sorted(self.entry.get(qn, ()))
            fi = self.model.functions[qn]
            rel = self.model.modules[fi.module].relpath
            for acq in self.facts[qn].acquires:
                if not acq.token.global_:
                    continue
                local_keys = {t.key for t in acq.held if t.global_}
                for lkey in sorted(set(entry_keys) | local_keys):
                    if lkey == acq.token.key:
                        continue
                    wl = self.edges.setdefault(
                        (lkey, acq.token.key), [])
                    if len(wl) < self._MAX_WITNESSES:
                        wl.append((qn, rel, acq.line,
                                   lkey not in local_keys))

    def cycles(self, max_cycles: int = 64) -> List[List[str]]:
        """Simple cycles in the lock-order graph, each a token list
        ``[t0, .., tk]`` meaning t0->t1->..->tk->t0.  Deterministic:
        SCCs found over sorted adjacency, one shortest cycle per
        in-SCC edge, deduped by node set.  Self-loops (reentrant
        RLock) are not cycles."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            if a == b:
                continue
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for k in adj:
            adj[k] = sorted(set(adj[k]))
        out: List[List[str]] = []
        seen_sets: Set[frozenset] = set()
        for scc in _tarjan_sccs(adj):
            if len(scc) < 2:
                continue
            nodes = set(scc)
            for a in sorted(nodes):
                for b in adj[a]:
                    if b not in nodes:
                        continue
                    back = _shortest_path(adj, b, a, nodes)
                    if back is None:
                        continue
                    cyc = [a] + back[:-1]
                    key = frozenset(cyc)
                    if key in seen_sets:
                        continue
                    seen_sets.add(key)
                    out.append(cyc)
                    if len(out) >= max_cycles:
                        return out
        return out

    # ------------------------------------------------------------ dumps
    def to_json(self) -> Dict:
        """The global lock-order graph, offline-inspection shape
        (``ray_tpu lint --lock-graph json``)."""
        nodes = sorted({k for e in self.edges for k in e})
        return {
            "nodes": nodes,
            "edges": [{
                "from": a, "to": b,
                "witnesses": [{"function": fn, "path": rel,
                               "line": line, "via_entry": ve}
                              for fn, rel, line, ve in wits],
            } for (a, b), wits in sorted(self.edges.items())],
            "cycles": self.cycles(),
        }

    def to_dot(self) -> str:
        cyc_nodes = {t for cyc in self.cycles() for t in cyc}
        lines = ["digraph lock_order {",
                 '  rankdir=LR; node [shape=box, fontsize=10];']
        for tok in sorted({k for e in self.edges for k in e}):
            style = ', color=red, penwidth=2' if tok in cyc_nodes \
                else ''
            lines.append(f'  "{tok}" [label="{_short_key(tok)}"'
                         f'{style}];')
        for (a, b), wits in sorted(self.edges.items()):
            fn, rel, line, _ve = wits[0]
            lines.append(f'  "{a}" -> "{b}" '
                         f'[label="{_short_fn(fn)}:{line}", '
                         f'fontsize=8];')
        lines.append("}")
        return "\n".join(lines) + "\n"


def _short_fn(qualname: str) -> str:
    """'pkg.mod:Cls.meth' -> 'mod:Cls.meth' (message-stable)."""
    mod, _, rest = qualname.partition(":")
    return f"{mod.rsplit('.', 1)[-1]}:{rest}"


def _short_key(token_key: str) -> str:
    mod, _, rest = token_key.partition(":")
    return f"{mod.rsplit('.', 1)[-1]}.{rest}"


def _tarjan_sccs(adj: Dict[str, List[str]]) -> List[List[str]]:
    """Iterative Tarjan over sorted nodes/neighbors (deterministic,
    recursion-free — lock graphs are small but cycles in them are
    exactly when a recursive walk would go deep)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(adj[nxt])))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(sorted(scc))
    return sccs


def _shortest_path(adj: Dict[str, List[str]], src: str, dst: str,
                   allowed: Set[str]) -> Optional[List[str]]:
    """BFS path src..dst (inclusive) within ``allowed``; sorted
    neighbor order keeps the chosen path deterministic."""
    if src == dst:
        return [src]
    prev: Dict[str, str] = {src: src}
    frontier = [src]
    while frontier:
        nxt_frontier = []
        for node in frontier:
            for nxt in adj.get(node, ()):
                if nxt not in allowed or nxt in prev:
                    continue
                prev[nxt] = node
                if nxt == dst:
                    path = [dst]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                nxt_frontier.append(nxt)
        frontier = nxt_frontier
    return None


# --------------------------------------------------------------------------
# hot-path classifier
# --------------------------------------------------------------------------

# ONE token table behind every hot-path heuristic, split into two
# profiles.  "dispatch": per-message/per-request control-plane verbs
# (log-hygiene's original set — eager work there is paid per op even
# when the result is discarded).  "device": per-step/per-chunk verbs of
# the jit/pjit hot loops (jit-in-hot-path's original set, plus the
# fwd/bwd shorthand the pipeline stages use).  The builder exemption is
# shared: make_train_step and friends exist to pay setup cost once.
_DISPATCH_TOKENS = (
    "submit", "dispatch", "enqueue", "push", "send", "put", "call",
    "request", "recv", "handle", "deliver", "ship", "ingest", "accept",
    "execute", "step", "read", "write", "flush", "poll", "emit",
    "sample", "observe", "record")
_DEVICE_TOKENS = (
    "dispatch", "handle", "submit", "execute", "request", "recv",
    "decode", "generate", "sample", "collect", "predict", "forward",
    "backward", "fwd", "bwd", "step", "loop", "round", "chunk",
    "process", "call")
_BUILDER_TOKENS = (
    "make", "build", "init", "create", "compile", "setup", "warmup")


def _token_re(tokens: Tuple[str, ...]) -> "re.Pattern":
    return re.compile(
        r"(?:^|_)(?:" + "|".join(tokens) + r")(?:_|$)|(?:^|_)on_", re.I)


class HotPathClassifier:
    """Name-based hot-path classification shared by log-hygiene,
    jit-in-hot-path, and the device-plane rules.

    ``dispatch_hot``: the message/RPC dispatch plane (no builder
    exemption — log-hygiene's historical behavior).  ``device_hot``:
    the jit/decode/train-step plane, builder-exempt.  ``sync_hot``:
    the union profile the host-device-sync rule uses — a blocking
    transfer hurts on EITHER plane, but builders/warmups are sync
    points by design."""

    def __init__(self):
        self._dispatch = _token_re(_DISPATCH_TOKENS)
        self._device = _token_re(_DEVICE_TOKENS)
        self._builder = re.compile(
            r"(?:^|_)(?:" + "|".join(_BUILDER_TOKENS) + r")(?:_|$)",
            re.I)

    def is_builder(self, name: str) -> bool:
        return bool(self._builder.search(name))

    def dispatch_hot(self, name: str) -> bool:
        return bool(self._dispatch.search(name))

    def device_hot(self, name: str) -> bool:
        return bool(self._device.search(name)) and \
            not self.is_builder(name)

    def sync_hot(self, name: str) -> bool:
        if self.is_builder(name):
            return False
        return bool(self._dispatch.search(name)
                    or self._device.search(name))


hot_paths = HotPathClassifier()


# --------------------------------------------------------------------------
# device-plane dataflow: the traced-value lattice
# --------------------------------------------------------------------------

def lvalue_key(expr: ast.AST) -> Optional[str]:
    """'self._apply' / 'cache' for Name/Attribute chains, ignoring
    the Load/Store context."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


def jit_build_desc(info: ModuleInfo, call: ast.Call) -> Optional[str]:
    """'jax.jit' / 'pjit' when this call builds a jit wrapper, else
    None.  Resolution is import-aware but tolerant of function-local
    ``import jax`` (the name itself then reads as the module)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in ("jit", "pjit"):
        base = f.value
        name = (base.id if isinstance(base, ast.Name)
                else getattr(base, "attr", ""))
        resolved = info.imports.get(name, name)
        if resolved == "jax" or resolved.startswith("jax."):
            return f"{name}.{f.attr}"
        return None
    if isinstance(f, ast.Name) and f.id in ("jit", "pjit"):
        resolved = info.imports.get(f.id, "")
        if resolved.startswith("jax"):
            return f.id
    return None


# Module roots whose call results live on device (the lattice's TRACED
# generators) and the host-side numpy root (results are host values;
# asarray/array of a traced input is the implicit-sync shape).
_DEVICE_MODULES = ("jax", "jax.numpy", "jax.lax", "jax.random",
                   "jax.nn", "jax.scipy", "jax.tree", "jax.tree_util",
                   "optax")
# jax.* calls whose results are host-side metadata (device handles,
# counts, backend names) — NOT arrays, never a sync to consume.
_JAX_HOST_FNS = frozenset((
    "devices", "local_devices", "device_count", "local_device_count",
    "process_index", "process_count", "default_backend",
    "live_arrays", "clear_caches", "make_mesh", "debug_print"))
# Bare-name fallbacks for function-local aliases the import table
# can't see ("jnp = self._jnp" in the serve engine).
_DEVICE_NAME_HINTS = {"jnp": "jax.numpy", "jax": "jax"}
_HOST_NAME_HINTS = {"np": "numpy", "numpy": "numpy"}


@dataclass
class JitBuild:
    """One ``jax.jit``/``pjit`` wrapper build site with the facts the
    device rules need: where it lives (``key`` — 'self._update',
    a module-level name, or None for anonymous builds that only feed
    the jitted-body index), what it donates, and whether any arg is
    static (bucketing evidence for recompile-hazard)."""
    qualname: str                # function containing the build
    module: str
    line: int
    desc: str                    # "jax.jit" / "pjit"
    key: Optional[str] = None
    donated: Tuple[int, ...] = ()
    donate_names: bool = False
    has_static: bool = False
    fn_qualnames: Tuple[str, ...] = ()

    def merged_with(self, other: "JitBuild") -> "JitBuild":
        """Conservative join when one attribute can hold either of two
        builds (a factory with a mesh and a mesh-less branch): only
        argnums BOTH donate count as donated; static-ness of either
        exempts (no false recompile findings)."""
        return JitBuild(
            qualname=self.qualname, module=self.module, line=self.line,
            desc=self.desc, key=self.key,
            donated=tuple(sorted(set(self.donated)
                                 & set(other.donated))),
            donate_names=self.donate_names or other.donate_names,
            has_static=self.has_static or other.has_static,
            fn_qualnames=tuple(sorted(set(self.fn_qualnames)
                                      | set(other.fn_qualnames))))


@dataclass
class SyncSite:
    """A host-forcing operation applied to a traced value."""
    line: int
    kind: str                    # "float()" / ".item()" / "truth-test"
    expr: str                    # printable traced expression
    annotated: bool              # inside a *.annotation(...) region


@dataclass
class WrapperArg:
    index: int
    key: Optional[str]           # lvalue key when Name/Attribute
    fresh_device_temp: bool      # inline jnp.asarray(...)-style temp
    dead_local: bool             # single-use local fed by a call
    scalar_desc: Optional[str]   # "len(xs)" when per-call-varying


@dataclass
class WrapperCall:
    """A call of a known jit wrapper, with everything missing-donation
    / recompile-hazard need about its arguments and targets."""
    line: int
    build: JitBuild
    args: List[WrapperArg]
    kw_scalars: List[Tuple[str, str]]  # (kwarg name, scalar desc)
    target_keys: Tuple[str, ...]       # lvalue keys when the call is
    #                                    the RHS of an assignment
    starred_from: Optional[int]        # index of first *args, if any
    in_loop: bool


@dataclass
class ShapeBranch:
    line: int
    desc: str


# A taint is False (host), True (may hold a jax.Array), or a tuple of
# bools — one per element of a tuple-shaped value, so unpacking
# ``toks, snapshot, t0 = pending`` taints only the device leaf, not
# the host bookkeeping riding in the same tuple.
Taint = object


def _join_taint(a, b):
    if a is True or b is True:
        return True
    if not a:
        return b
    if not b:
        return a
    if isinstance(a, tuple) and isinstance(b, tuple) and \
            len(a) == len(b):
        return tuple(x or y for x, y in zip(a, b))
    return True


def _taint_any(t) -> bool:
    return any(t) if isinstance(t, tuple) else bool(t)


@dataclass
class FuncFlow:
    """Per-function device-plane facts from one abstract-interpretation
    pass: the sites rules turn into findings, plus the summary bits
    (returns/assigns traced values) the interprocedural fixpoint
    propagates."""
    sync_sites: List[SyncSite] = field(default_factory=list)
    wrapper_calls: List[WrapperCall] = field(default_factory=list)
    returns_traced: bool = False
    # per-element taints of literal-tuple returns; None once a traced
    # NON-tuple return poisons the element view
    return_tuples: List[Tuple[bool, ...]] = field(default_factory=list)
    returns_poisoned: bool = False
    # (class qualname, attr) assigned a traced value in this function
    traced_attr_assigns: Set[Tuple[str, str]] = field(
        default_factory=set)
    # callee qualname -> {param name: taint} observed at call sites
    callee_traced_params: Dict[str, Dict[str, Taint]] = field(
        default_factory=dict)


class DeviceFlow:
    """The conservative traced-value lattice over the package.

    A value is TRACED when it may hold a ``jax.Array`` (or a pytree of
    them): the return of a jitted wrapper, a ``jnp.*``/``jax.*`` call
    result (collectives included), a traced attribute (model params,
    KV caches), or anything data-derived from one (subscripts, method
    calls, arithmetic).  ``jax.device_get`` / ``float()`` / ``np.
    asarray()`` results are HOST — the conversions themselves are the
    implicit-sync sites host-device-sync reports.

    Tracedness propagates intraprocedurally (statement-ordered, with
    strong updates so an explicit ``device_get`` kills the taint) and
    interprocedurally over the call graph's confident edges, exactly
    the kinds LockAnalysis trusts: callee returns flow to caller
    assignment targets, traced arguments flow to callee parameters,
    traced ``self.X =`` assignments flow class-wide.  All three
    summaries grow monotonically, so the worklist fixpoint terminates;
    iteration is sorted everywhere for byte-identical runs."""

    _PROPAGATE_KINDS = ("self", "local", "module", "import", "init")
    _SYNC_BUILTINS = ("float", "int", "bool")

    def __init__(self, model: ProjectModel):
        self.model = model
        # wrapper registries
        self._attr_builds: Dict[Tuple[str, str],
                                Dict[str, JitBuild]] = {}
        self._local_builds: Dict[Tuple[str, str], JitBuild] = {}
        self._module_builds: Dict[Tuple[str, str], JitBuild] = {}
        self.builds: List[JitBuild] = []
        self.jitted: Set[str] = set()          # jitted-body qualnames
        self.dispatchers: Set[str] = set()     # _run(fn, *a) shims
        self.shape_branches: Dict[str, List[ShapeBranch]] = {}
        self.mesh_axes: Set[str] = set()       # constructible axes
        # interprocedural summaries (monotone)
        self.returns_traced: Set[str] = set()
        # qualname -> per-element taints when every traced return is a
        # literal tuple (callers unpacking it get leaf-level taint)
        self.returns_tuple: Dict[str, Tuple[bool, ...]] = {}
        self.param_traced: Dict[str, Dict[str, Taint]] = {}
        self.traced_attrs: Dict[str, Set[str]] = {}
        self.flows: Dict[str, FuncFlow] = {}
        self._rev_edges: Dict[str, Set[str]] = {}
        self._class_methods: Dict[str, List[str]] = {}

        self._scan_builds()
        self._scan_dispatchers()
        self._mark_jitted_bodies()
        self._scan_mesh_axes()
        self._build_reverse_edges()
        self._fixpoint()
        self._scan_shape_branches()

    # ------------------------------------------------- wrapper registry
    def _scan_builds(self) -> None:
        for modname in sorted(self.model.modules):
            info = self.model.modules[modname]
            # module-level "step = jax.jit(...)" bindings
            for node in info.tree.body:
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        isinstance(node.value, ast.Call):
                    build = self._parse_build(info, None, node.value)
                    if build is not None:
                        build.key = node.targets[0].id
                        self._module_builds[
                            (modname, build.key)] = build
        for qn in sorted(self.model.functions):
            fi = self.model.functions[qn]
            info = self.model.modules[fi.module]
            for node in self.model.walk_own(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                build = self._parse_build(info, fi, node)
                if build is None:
                    continue
                self.builds.append(build)
            for node in self.model.walk_own(fi.node):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.value, ast.Call):
                    build = self._parse_build(info, fi, node.value,
                                              register=False)
                    if build is None:
                        continue
                    key = lvalue_key(node.targets[0])
                    if key is None:
                        continue
                    build.key = key
                    if key.startswith("self.") and fi.cls is not None:
                        self._register_attr(fi.module, fi.cls,
                                            key[5:], build)
                    elif "." not in key:
                        self._local_builds[(qn, key)] = build
        # attrs filled from a factory: self._update = self._make_...()
        for qn in sorted(self.model.functions):
            fi = self.model.functions[qn]
            if fi.cls is None:
                continue
            info = self.model.modules[fi.module]
            for node in self.model.walk_own(fi.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)):
                    continue
                key = lvalue_key(node.targets[0])
                if key is None or not key.startswith("self."):
                    continue
                hit = self.model._resolve_call_edge(info, fi,
                                                    node.value)
                if hit is None or hit[1] not in self._PROPAGATE_KINDS:
                    continue
                build = self._returned_build(hit[0])
                if build is not None:
                    self._register_attr(fi.module, fi.cls, key[5:],
                                        build)

    def _register_attr(self, module: str, cls: str, attr: str,
                       build: JitBuild) -> None:
        slot = self._attr_builds.setdefault((module, cls), {})
        if attr in slot:
            slot[attr] = slot[attr].merged_with(build)
        else:
            slot[attr] = build

    def _parse_build(self, info: ModuleInfo, fi: Optional[FuncInfo],
                     call: ast.Call,
                     register: bool = True) -> Optional[JitBuild]:
        desc = jit_build_desc(info, call)
        if desc is None:
            return None
        donated: Tuple[int, ...] = ()
        donate_names = False
        has_static = False
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, int):
                    donated = (v.value,)
                elif isinstance(v, (ast.Tuple, ast.List)):
                    donated = tuple(
                        e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int))
            elif kw.arg == "donate_argnames":
                donate_names = True
            elif kw.arg in ("static_argnums", "static_argnames"):
                has_static = True
        fn_qns: List[str] = []
        if call.args:
            fn_qns = self._resolve_callable(info, fi, call.args[0])
        qn = fi.qualname if fi is not None else f"{info.name}:<module>"
        build = JitBuild(qualname=qn, module=info.name,
                         line=call.lineno, desc=desc, donated=donated,
                         donate_names=donate_names,
                         has_static=has_static,
                         fn_qualnames=tuple(fn_qns))
        return build

    def _resolve_callable(self, info: ModuleInfo,
                          fi: Optional[FuncInfo],
                          expr: ast.AST) -> List[str]:
        """Project qualnames a jit build's first argument may name."""
        if isinstance(expr, ast.Name):
            if fi is not None:
                hit = self.model._resolve_name_kind(info, fi, expr.id)
                if hit is not None:
                    return [hit[0]]
            qn = f"{info.name}:{expr.id}"
            if qn in self.model.functions:
                return [qn]
            imported = info.imports.get(expr.id)
            if imported:
                mod, _, sym = imported.rpartition(".")
                qn = f"{mod}:{sym}"
                if qn in self.model.functions:
                    return [qn]
        elif isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            target = info.imports.get(expr.value.id)
            if target in self.model.modules:
                qn = f"{target}:{expr.attr}"
                if qn in self.model.functions:
                    return [qn]
        elif isinstance(expr, ast.Call):
            # functools.partial(fn, ...) and friends: chase arg 0
            if expr.args:
                return self._resolve_callable(info, fi, expr.args[0])
        return []

    def _returned_build(self, qn: str) -> Optional[JitBuild]:
        """The JitBuild a factory function returns, when its return
        statements are jit builds (directly, or a local bound to
        one).  Multiple return branches merge conservatively."""
        fi = self.model.functions.get(qn)
        if fi is None:
            return None
        info = self.model.modules[fi.module]
        found: Optional[JitBuild] = None
        for node in self.model.walk_own(fi.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            build: Optional[JitBuild] = None
            if isinstance(node.value, ast.Call):
                build = self._parse_build(info, fi, node.value,
                                          register=False)
            elif isinstance(node.value, ast.Name):
                build = self._local_builds.get((qn, node.value.id))
            if build is None:
                continue
            found = build if found is None else \
                found.merged_with(build)
        return found

    # --------------------------------------------------- jitted bodies
    def _scan_dispatchers(self) -> None:
        """Functions that only forward to their first parameter
        (``def _run(self, fn, *args): return fn(*args)``) — a wrapper
        passed through one still counts as called."""
        for qn in sorted(self.model.functions):
            fi = self.model.functions[qn]
            args = [a.arg for a in fi.node.args.args
                    if a.arg != "self"]
            if not args:
                continue
            p0 = args[0]
            returns = [n for n in self.model.walk_own(fi.node)
                       if isinstance(n, ast.Return)
                       and n.value is not None]
            if not returns:
                continue
            if all(isinstance(r.value, ast.Call)
                   and isinstance(r.value.func, ast.Name)
                   and r.value.func.id == p0 for r in returns):
                self.dispatchers.add(qn)

    def _mark_jitted_bodies(self) -> None:
        """Every function a jit build compiles, closed transitively
        over confident call edges: code that runs under trace cannot
        host-sync (it would fail at trace time), so the sync rule
        skips it wholesale."""
        pending = set()
        for build in self.builds:
            pending.update(build.fn_qualnames)
        for builds in (self._module_builds, self._local_builds):
            for b in builds.values():
                pending.update(b.fn_qualnames)
        for slot in self._attr_builds.values():
            for b in slot.values():
                pending.update(b.fn_qualnames)
        while pending:
            nxt: Set[str] = set()
            for qn in sorted(pending):
                if qn in self.jitted:
                    continue
                self.jitted.add(qn)
                for e in self.model.call_edges.get(qn, ()):
                    if e.kind in self._PROPAGATE_KINDS and \
                            e.target not in self.jitted:
                        nxt.add(e.target)
            pending = nxt

    # ------------------------------------------------------- mesh axes
    def _scan_mesh_axes(self) -> None:
        """Axis names a mesh constructible in this package can carry:
        ``Mesh(...)/AbstractMesh(...)`` axis tuples, ``*AXIS*``
        module constants, and the MeshSpec/ShardingRules field
        vocabulary.  sharding-contract checks literal PartitionSpec
        axes against this set."""
        def strings_in(node: ast.AST) -> List[str]:
            """DIRECT string literals only — a ``tuple(d["axis_names"])``
            expression contributes nothing (its subscript key is not an
            axis name)."""
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                return [node.value]
            if isinstance(node, (ast.Tuple, ast.List)):
                out: List[str] = []
                for e in node.elts:
                    out.extend(strings_in(e))
                return out
            return []

        for modname in sorted(self.model.modules):
            info = self.model.modules[modname]
            for node in ast.walk(info.tree):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        ("AXIS" in node.targets[0].id.upper()
                         or "AXES" in node.targets[0].id.upper()) and \
                        isinstance(node.value, (ast.Tuple, ast.List)):
                    self.mesh_axes.update(strings_in(node.value))
                elif isinstance(node, ast.Call):
                    fname = (node.func.attr
                             if isinstance(node.func, ast.Attribute)
                             else getattr(node.func, "id", ""))
                    if fname in ("Mesh", "AbstractMesh", "make_mesh"):
                        for kw in node.keywords:
                            if kw.arg == "axis_names":
                                self.mesh_axes.update(
                                    strings_in(kw.value))
                        if len(node.args) >= 2:
                            self.mesh_axes.update(
                                strings_in(node.args[1]))
                    elif fname in ("ShardingRules", "MeshSpec"):
                        for kw in node.keywords:
                            if isinstance(kw.value, ast.Constant) and \
                                    isinstance(kw.value.value, str):
                                self.mesh_axes.add(kw.value.value)
                elif isinstance(node, ast.ClassDef) and \
                        node.name in ("ShardingRules", "MeshSpec"):
                    for item in node.body:
                        if isinstance(item, ast.AnnAssign) and \
                                isinstance(item.target, ast.Name):
                            self.mesh_axes.add(item.target.id)
                            if item.value is not None:
                                self.mesh_axes.update(
                                    strings_in(item.value))

    # --------------------------------------------------- the fixpoint
    def _build_reverse_edges(self) -> None:
        for qn in sorted(self.model.call_edges):
            for e in self.model.call_edges[qn]:
                if e.kind in self._PROPAGATE_KINDS:
                    self._rev_edges.setdefault(e.target,
                                               set()).add(qn)
        for cqn in sorted(self.model.classes):
            ci = self.model.classes[cqn]
            self._class_methods[cqn] = sorted(ci.methods.values())

    def _fixpoint(self) -> None:
        pending = set(self.model.functions)
        rounds = 0
        while pending and rounds < 24:
            rounds += 1
            requeue: Set[str] = set()
            for qn in sorted(pending):
                flow = _FlowInterp(self, qn).run()
                self.flows[qn] = flow
                if flow.returns_traced and \
                        qn not in self.returns_traced:
                    self.returns_traced.add(qn)
                    requeue.update(self._rev_edges.get(qn, ()))
                rt: Optional[Tuple[bool, ...]] = None
                if flow.return_tuples and not flow.returns_poisoned:
                    rt = flow.return_tuples[0]
                    for t in flow.return_tuples[1:]:
                        joined = _join_taint(rt, t)
                        rt = joined if isinstance(joined, tuple) \
                            else None
                        if rt is None:
                            break
                if rt is not None and \
                        self.returns_tuple.get(qn) != rt:
                    self.returns_tuple[qn] = rt
                    requeue.update(self._rev_edges.get(qn, ()))
                elif rt is None and qn in self.returns_tuple:
                    del self.returns_tuple[qn]
                    requeue.update(self._rev_edges.get(qn, ()))
                for cls_qn, attr in sorted(flow.traced_attr_assigns):
                    attrs = self.traced_attrs.setdefault(cls_qn,
                                                         set())
                    if attr not in attrs:
                        attrs.add(attr)
                        requeue.update(
                            self._class_methods.get(cls_qn, ()))
                for callee in sorted(flow.callee_traced_params):
                    taints = flow.callee_traced_params[callee]
                    have = self.param_traced.setdefault(callee, {})
                    for name in sorted(taints):
                        new = _join_taint(have.get(name, False),
                                          taints[name])
                        if new != have.get(name, False):
                            have[name] = new
                            requeue.add(callee)
            pending = requeue

    def attr_traced(self, module: str, cls: Optional[str],
                    attr: str) -> bool:
        if cls is None:
            return False
        seen: Set[str] = set()
        stack = [f"{module}:{cls}"]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            if attr in self.traced_attrs.get(key, ()):
                return True
            ci = self.model.classes.get(key)
            if ci is None:
                continue
            for base in ci.bases:
                if f"{ci.module}:{base}" in self.model.classes:
                    stack.append(f"{ci.module}:{base}")
        return False

    def attr_build(self, module: str, cls: Optional[str],
                   attr: str) -> Optional[JitBuild]:
        if cls is None:
            return None
        seen: Set[str] = set()
        stack = [(module, cls)]
        while stack:
            mk = stack.pop()
            if mk in seen:
                continue
            seen.add(mk)
            hit = self._attr_builds.get(mk, {}).get(attr)
            if hit is not None:
                return hit
            ci = self.model.classes.get(f"{mk[0]}:{mk[1]}")
            if ci is None:
                continue
            for base in ci.bases:
                if f"{ci.module}:{base}" in self.model.classes:
                    stack.append((ci.module, base))
        return None

    # ------------------------------------------------- shape branches
    def _scan_shape_branches(self) -> None:
        """Python ``if``/``while`` on ``.shape``/``len()`` inside
        jitted bodies: legal (shapes are static under trace) but each
        distinct shape class re-traces — the static half of the
        recompile-storm signal."""
        for qn in sorted(self.jitted):
            fi = self.model.functions.get(qn)
            if fi is None or hot_paths.is_builder(fi.name):
                continue
            sites: List[ShapeBranch] = []
            for node in self.model.walk_own(fi.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                for sub in ast.walk(node.test):
                    if (isinstance(sub, ast.Attribute)
                            and sub.attr in ("shape", "ndim")) or \
                            (isinstance(sub, ast.Call)
                             and isinstance(sub.func, ast.Name)
                             and sub.func.id == "len"):
                        try:
                            desc = ast.unparse(node.test)
                        except Exception:
                            desc = "<test>"
                        sites.append(ShapeBranch(node.lineno, desc))
                        break
            if sites:
                self.shape_branches[qn] = sites


class _FlowInterp:
    """One statement-ordered abstract-interpretation pass over one
    function: ``env`` maps local names and ``self.X`` keys to
    may-be-traced, with strong updates (``stats = jax.device_get(
    stats)`` kills the taint for everything after it).  ``if`` runs
    both arms on copies and joins with union; loop bodies run twice so
    a value traced at the bottom taints the top.  Side products are
    the SyncSites and WrapperCalls the device rules read."""

    def __init__(self, df: DeviceFlow, qn: str):
        self.df = df
        self.qn = qn
        self.fi = df.model.functions[qn]
        self.info = df.model.modules[self.fi.module]
        self.flow = FuncFlow()
        self.env: Dict[str, bool] = {}
        # name -> per-element taints for locals known to hold a tuple
        # (a mixed device/host bundle unpacks leaf-by-leaf)
        self._tuples: Dict[str, Tuple[bool, ...]] = {}
        self._ann_depth = 0
        self._loop_depth = 0
        self._params = [a.arg for a in self._all_args(self.fi.node)]
        # name -> Load-occurrence count / Call-RHS-assignment count,
        # for the dead-local judgement
        self._loads: Dict[str, int] = {}
        self._call_assigns: Dict[str, int] = {}
        self._other_assigns: Dict[str, int] = {}
        for node in df.model.walk_own(self.fi.node):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    self._loads[node.id] = \
                        self._loads.get(node.id, 0) + 1
            if isinstance(node, ast.Assign):
                is_call = isinstance(node.value, ast.Call)
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            slot = (self._call_assigns if is_call
                                    else self._other_assigns)
                            slot[sub.id] = slot.get(sub.id, 0) + 1
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                                   ast.For)):
                tgt = getattr(node, "target", None)
                if tgt is not None:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            self._other_assigns[sub.id] = \
                                self._other_assigns.get(sub.id, 0) + 1

    @staticmethod
    def _all_args(node: ast.AST) -> List[ast.arg]:
        a = node.args
        return (list(a.posonlyargs) + list(a.args)
                + list(a.kwonlyargs))

    def run(self) -> FuncFlow:
        seeds = self.df.param_traced.get(self.qn, {})
        for name in sorted(seeds):
            taint = seeds[name]
            self.env[name] = _taint_any(taint)
            if isinstance(taint, tuple):
                self._tuples[name] = taint
        self._block(self.fi.node.body)
        return self.flow

    # --------------------------------------------------- statements
    def _block(self, stmts: List[ast.stmt]) -> None:
        for node in stmts:
            self._stmt(node)

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                       # executes elsewhere
        if isinstance(node, ast.Assign):
            tkeys = tuple(k for t in node.targets
                          for k in self._target_keys(t))
            traced = self._eval(node.value, targets=tkeys)
            elems = self._value_tuple(node.value, traced)
            for t in node.targets:
                if elems is not None and \
                        isinstance(t, (ast.Tuple, ast.List)) and \
                        len(t.elts) == len(elems) and \
                        not any(isinstance(e, ast.Starred)
                                for e in t.elts):
                    for e, et in zip(t.elts, elems):
                        self._bind(e, et)
                    continue
                self._bind(t, traced)
                if isinstance(t, ast.Name):
                    if elems is not None:
                        self._tuples[t.id] = elems
                    else:
                        self._tuples.pop(t.id, None)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                tkeys = tuple(self._target_keys(node.target))
                traced = self._eval(node.value, targets=tkeys)
                self._bind(node.target, traced)
        elif isinstance(node, ast.AugAssign):
            traced = self._eval(node.value)
            key = lvalue_key(node.target)
            if key is not None:
                old = self._lookup(key, node.target)
                self._set(key, old or traced, node.target)
        elif isinstance(node, ast.Expr):
            self._eval(node.value)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                if isinstance(node.value, ast.Tuple):
                    elems = tuple(bool(self._eval(e))
                                  for e in node.value.elts)
                    self.flow.return_tuples.append(elems)
                    if any(elems):
                        self.flow.returns_traced = True
                elif self._eval(node.value):
                    self.flow.returns_traced = True
                    # a traced non-tuple return: callers can no
                    # longer rely on the per-element view
                    self.flow.returns_poisoned = True
        elif isinstance(node, (ast.If, ast.While)):
            self._truth_test(node.test)
            if isinstance(node, ast.While):
                self._loop_depth += 1
                for _ in range(2):
                    self._block(node.body)
                self._loop_depth -= 1
                self._block(node.orelse)
            else:
                saved = dict(self.env)
                self._block(node.body)
                then_env = self.env
                self.env = dict(saved)
                self._block(node.orelse)
                for k in sorted(set(then_env) | set(self.env)):
                    self.env[k] = then_env.get(k, False) or \
                        self.env.get(k, False)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            it_traced = self._eval(node.iter)
            self._bind(node.target, it_traced)
            self._loop_depth += 1
            for _ in range(2):
                self._block(node.body)
            self._loop_depth -= 1
            self._block(node.orelse)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            annotated = any(self._is_annotation_cm(item.context_expr)
                            for item in node.items)
            for item in node.items:
                traced = self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, traced)
            if annotated:
                self._ann_depth += 1
            self._block(node.body)
            if annotated:
                self._ann_depth -= 1
        elif isinstance(node, ast.Try):
            self._block(node.body)
            for h in node.handlers:
                self._block(h.body)
            self._block(node.orelse)
            self._block(node.finalbody)
        elif isinstance(node, ast.Assert):
            self._truth_test(node.test)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self._eval(node.exc)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                key = lvalue_key(t)
                if key is not None and key in self.env:
                    del self.env[key]

    def _value_tuple(self, expr: ast.expr, traced: bool
                     ) -> Optional[Tuple[bool, ...]]:
        """Per-element taints when this (already-evaluated) RHS is
        known tuple-shaped: a local carrying one, or a call whose
        callee returns literal tuples.  No re-evaluation — the lookup
        must not duplicate sync sites."""
        if not traced:
            return None
        if isinstance(expr, ast.Name):
            return self._tuples.get(expr.id)
        if isinstance(expr, ast.Call):
            edge = self.df.model._resolve_call_edge(self.info,
                                                    self.fi, expr)
            if edge is not None and \
                    edge[1] in DeviceFlow._PROPAGATE_KINDS:
                return self.df.returns_tuple.get(edge[0])
        return None

    def _target_keys(self, target: ast.AST) -> List[str]:
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for e in target.elts:
                out.extend(self._target_keys(e))
            return out
        key = lvalue_key(target)
        return [key] if key is not None else []

    def _bind(self, target: ast.AST, traced: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, traced)
            return
        if isinstance(target, ast.Starred):
            self._bind(target.value, traced)
            return
        if isinstance(target, ast.Subscript):
            # container[k] = traced taints the container itself —
            # self._inputs[i] = activations makes _inputs a traced
            # store whose .pop() later yields a traced value.
            if traced:
                key = lvalue_key(target.value)
                if key is not None:
                    self._set(key, True, target.value)
            return
        key = lvalue_key(target)
        if key is not None:
            self._set(key, traced, target)

    def _set(self, key: str, traced: bool, node: ast.AST) -> None:
        self.env[key] = traced
        if traced and key.startswith("self.") and \
                "." not in key[5:] and self.fi.cls is not None:
            self.flow.traced_attr_assigns.add(
                (f"{self.fi.module}:{self.fi.cls}", key[5:]))

    def _lookup(self, key: str, node: ast.AST) -> bool:
        if key in self.env:
            return self.env[key]
        if key.startswith("self.") and "." not in key[5:]:
            return self.df.attr_traced(self.fi.module, self.fi.cls,
                                       key[5:])
        return False

    # -------------------------------------------------- expressions
    def _truth_test(self, test: ast.expr) -> None:
        """Truth-testing a traced value is a blocking device->host
        read; ``x is None`` guards are identity checks and stay
        host-side."""
        if isinstance(test, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
            for sub in [test.left] + list(test.comparators):
                self._eval(sub)
            return
        if self._eval(test):
            self._sync(test, "truth-test", test)
        elif isinstance(test, ast.BoolOp):
            for v in test.values:
                if self._eval(v):
                    self._sync(v, "truth-test", v)

    def _sync(self, node: ast.AST, kind: str,
              expr: ast.AST) -> None:
        try:
            desc = ast.unparse(expr)
        except Exception:
            desc = "<expr>"
        if len(desc) > 60:
            desc = desc[:57] + "..."
        self.flow.sync_sites.append(SyncSite(
            line=getattr(node, "lineno", self.fi.line), kind=kind,
            expr=desc, annotated=self._ann_depth > 0))

    def _is_annotation_cm(self, expr: ast.expr) -> bool:
        return isinstance(expr, ast.Call) and \
            isinstance(expr.func, ast.Attribute) and \
            expr.func.attr == "annotation"

    def _module_root(self, expr: ast.expr) -> Optional[str]:
        """The fully-qualified module a Name/Attribute base refers to
        ('jnp' -> 'jax.numpy'), import-table first, then the bare-name
        conventions local aliases like ``jnp = self._jnp`` follow."""
        if isinstance(expr, ast.Name):
            hit = self.info.imports.get(expr.id)
            if hit:
                return hit
            return _DEVICE_NAME_HINTS.get(expr.id) or \
                _HOST_NAME_HINTS.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return {"_jnp": "jax.numpy", "_jax": "jax",
                    "_np": "numpy"}.get(expr.attr)
        return None

    def _eval(self, expr: ast.expr,
              targets: Tuple[str, ...] = ()) -> bool:
        if isinstance(expr, ast.Call):
            return self._eval_call(expr, targets)
        if isinstance(expr, (ast.Name, ast.Attribute)):
            key = lvalue_key(expr)
            if key is not None:
                if key in self.env:
                    return self.env[key]
                if isinstance(expr, ast.Name):
                    return False
                return self._lookup(key, expr)
            # attribute OF a computed value: metadata access
            # (x.shape, x.dtype) — host-side, never a sync
            if isinstance(expr, ast.Attribute):
                self._eval(expr.value)
            return False
        if isinstance(expr, ast.Subscript):
            traced = self._eval(expr.value)
            self._eval(expr.slice)
            return traced
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any([self._eval(e) for e in expr.elts])
        if isinstance(expr, ast.Dict):
            vals = [self._eval(v) for v in expr.values
                    if v is not None]
            for k in expr.keys:
                if k is not None:
                    self._eval(k)
            return any(vals)
        if isinstance(expr, ast.Starred):
            return self._eval(expr.value)
        if isinstance(expr, ast.BinOp):
            left = self._eval(expr.left)
            right = self._eval(expr.right)
            return left or right
        if isinstance(expr, ast.UnaryOp):
            return self._eval(expr.operand)
        if isinstance(expr, ast.BoolOp):
            return any([self._eval(v) for v in expr.values])
        if isinstance(expr, ast.Compare):
            vals = [self._eval(expr.left)]
            vals += [self._eval(c) for c in expr.comparators]
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In,
                                   ast.NotIn)) for op in expr.ops):
                return False
            return any(vals)
        if isinstance(expr, ast.IfExp):
            self._truth_test(expr.test)
            body = self._eval(expr.body)
            orelse = self._eval(expr.orelse)
            return body or orelse
        if isinstance(expr, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            return self._eval_comp(expr)
        if isinstance(expr, ast.JoinedStr):
            for v in expr.values:
                if isinstance(v, ast.FormattedValue):
                    self._eval(v.value)
            return False
        if isinstance(expr, (ast.Await, ast.YieldFrom)):
            return self._eval(expr.value)
        if isinstance(expr, ast.Yield):
            if expr.value is not None:
                self._eval(expr.value)
            return False
        if isinstance(expr, ast.Lambda):
            return False
        if isinstance(expr, ast.NamedExpr):
            traced = self._eval(expr.value)
            self._bind(expr.target, traced)
            return traced
        return False

    def _eval_comp(self, expr: ast.expr) -> bool:
        saved = dict(self.env)
        for gen in expr.generators:
            it_traced = self._eval(gen.iter)
            self._bind(gen.target, it_traced)
            for cond in gen.ifs:
                self._truth_test(cond)
        if isinstance(expr, ast.DictComp):
            self._eval(expr.key)
            traced = self._eval(expr.value)
        else:
            traced = self._eval(expr.elt)
        self.env = saved
        return traced

    def _fstring_traced(self, expr: ast.expr) -> bool:
        if not isinstance(expr, ast.JoinedStr):
            return False
        return any(self._eval(v.value) for v in expr.values
                   if isinstance(v, ast.FormattedValue))

    # --------------------------------------------------------- calls
    def _eval_call(self, call: ast.Call,
                   targets: Tuple[str, ...] = ()) -> bool:
        f = call.func
        fname = (f.id if isinstance(f, ast.Name)
                 else f.attr if isinstance(f, ast.Attribute) else "")

        # -- explicit host/device boundary builtins ------------------
        if isinstance(f, ast.Name):
            if f.id in DeviceFlow._SYNC_BUILTINS and \
                    len(call.args) == 1 and not call.keywords:
                if self._eval(call.args[0]):
                    self._sync(call, f"{f.id}()", call.args[0])
                return False
            if f.id == "print":
                for a in call.args:
                    if self._eval(a) or self._fstring_traced(a):
                        self._sync(call, "print", a)
                        break
                for kw in call.keywords:
                    self._eval(kw.value)
                return False
            if f.id == "len":
                for a in call.args:
                    self._eval(a)
                return False           # shape metadata, not a sync

        if isinstance(f, ast.Attribute):
            root = self._module_root(f.value)
            base_traced = (self._eval(f.value)
                           if root is None else False)
            if root is not None and (root == "numpy"
                                     or root.startswith("numpy.")):
                if f.attr in ("asarray", "array", "copy") and \
                        call.args and self._eval(call.args[0]):
                    self._sync(call, f"np.{f.attr}()", call.args[0])
                for a in call.args[1:]:
                    self._eval(a)
                for kw in call.keywords:
                    self._eval(kw.value)
                return False
            if root is not None and (root in _DEVICE_MODULES
                                     or root.startswith("jax.")):
                for a in call.args:
                    self._eval(a)
                for kw in call.keywords:
                    self._eval(kw.value)
                if f.attr == "device_get":
                    return False       # explicit transfer: host out
                if f.attr in _JAX_HOST_FNS:
                    return False       # host-side metadata
                # block_until_ready and everything else: device out
                return True
            if f.attr == "item" and base_traced and not call.args:
                self._sync(call, ".item()", f.value)
                return False
            if f.attr == "block_until_ready" and base_traced:
                return True
            if base_traced:
                # method on a traced pytree/array (.items(), .get(),
                # .pop(), .astype(), dict views...) keeps tracedness
                for a in call.args:
                    self._eval(a)
                for kw in call.keywords:
                    self._eval(kw.value)
                return True

        # -- known jit wrapper? --------------------------------------
        build, shifted = self._wrapper_of(call)
        if build is not None:
            self._record_wrapper(call, build, shifted, targets)
            return True

        # -- project call edge: propagate args in, returns out -------
        edge = self.df.model._resolve_call_edge(self.info, self.fi,
                                                call)
        arg_taints: List[Taint] = []
        for a in call.args:
            t: Taint = self._eval(a)
            if t and isinstance(a, ast.Name) and \
                    a.id in self._tuples:
                t = self._tuples[a.id]
            arg_taints.append(t)
        kw_traced = [(kw.arg, self._eval(kw.value))
                     for kw in call.keywords]
        if edge is not None and \
                edge[1] in DeviceFlow._PROPAGATE_KINDS:
            callee, _kind = edge
            cfi = self.df.model.functions.get(callee)
            if cfi is not None:
                params = [a.arg for a in self._all_args(cfi.node)]
                if params and params[0] == "self":
                    params = params[1:]
                hot: Dict[str, Taint] = {
                    p: arg_taints[i]
                    for i, p in enumerate(params)
                    if i < len(arg_taints)
                    and _taint_any(arg_taints[i])}
                for kw, t in kw_traced:
                    if t and kw in params:
                        hot[kw] = True
                if hot:
                    slot = self.flow.callee_traced_params.setdefault(
                        callee, {})
                    for name in sorted(hot):
                        slot[name] = _join_taint(
                            slot.get(name, False), hot[name])
            return callee in self.df.returns_traced
        return False

    def _wrapper_of(self, call: ast.Call
                    ) -> Tuple[Optional[JitBuild], int]:
        """(build, arg shift) when this call invokes a known jit
        wrapper — directly, or through a ``_run(fn, *args)``-shaped
        dispatcher whose first argument is the wrapper."""
        build = self._build_for_expr(call.func)
        if build is not None:
            return build, 0
        edge = self.df.model._resolve_call_edge(self.info, self.fi,
                                                call)
        if edge is not None and edge[0] in self.df.dispatchers \
                and call.args:
            inner = self._build_for_expr(call.args[0])
            if inner is not None:
                return inner, 1
        return None, 0

    def _build_for_expr(self, expr: ast.expr) -> Optional[JitBuild]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return self.df.attr_build(self.fi.module, self.fi.cls,
                                      expr.attr)
        if isinstance(expr, ast.Name):
            hit = self.df._local_builds.get((self.qn, expr.id))
            if hit is not None:
                return hit
            return self.df._module_builds.get(
                (self.fi.module, expr.id))
        return None

    def _record_wrapper(self, call: ast.Call, build: JitBuild,
                        shift: int,
                        targets: Tuple[str, ...]) -> None:
        args: List[WrapperArg] = []
        starred_from: Optional[int] = None
        for i, a in enumerate(call.args[shift:]):
            if isinstance(a, ast.Starred):
                if starred_from is None:
                    starred_from = i
                self._eval(a.value)
                continue
            self._eval(a)
            args.append(WrapperArg(
                index=i, key=lvalue_key(a),
                fresh_device_temp=self._is_fresh_device_temp(a),
                dead_local=self._is_dead_local(a),
                scalar_desc=self._scalar_desc(a)))
        kw_scalars: List[Tuple[str, str]] = []
        for kw in call.keywords:
            self._eval(kw.value)
            if kw.arg is not None:
                desc = self._scalar_desc(kw.value)
                if desc is not None:
                    kw_scalars.append((kw.arg, desc))
        self.flow.wrapper_calls.append(WrapperCall(
            line=call.lineno, build=build, args=args,
            kw_scalars=kw_scalars, target_keys=targets,
            starred_from=starred_from,
            in_loop=self._loop_depth > 0))

    def _is_fresh_device_temp(self, expr: ast.expr) -> bool:
        """An inline jnp.*/jax.* call: a device value nothing else can
        reference — dead the moment the wrapper consumes it."""
        if not (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)):
            return False
        root = self._module_root(expr.func.value)
        return root is not None and (root in _DEVICE_MODULES
                                     or root.startswith("jax."))

    def _is_dead_local(self, expr: ast.expr) -> bool:
        """A plain local whose ONLY load is this argument, bound
        exactly once from a call result: the buffer has no other
        referent, so donating it is free."""
        if not isinstance(expr, ast.Name) or self._loop_depth > 0:
            return False
        name = expr.id
        if name in self._params:
            return False
        return (self._loads.get(name, 0) == 1
                and self._call_assigns.get(name, 0) == 1
                and self._other_assigns.get(name, 0) == 0)

    def _scalar_desc(self, expr: ast.expr) -> Optional[str]:
        """Per-call-varying Python scalar shapes that re-trigger
        tracing when fed to a jitted callee as dynamic args:
        ``len(x)``, ``int(x)``, ``x.shape[i]``."""
        if isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Name):
            if expr.func.id == "len" and expr.args:
                return _safe_unparse(expr)
            if expr.func.id == "int" and expr.args and \
                    not isinstance(expr.args[0], ast.Constant):
                return _safe_unparse(expr)
        if isinstance(expr, ast.Subscript) and \
                isinstance(expr.value, ast.Attribute) and \
                expr.value.attr == "shape":
            return _safe_unparse(expr)
        return None


def _safe_unparse(expr: ast.AST) -> str:
    try:
        out = ast.unparse(expr)
    except Exception:
        return "<expr>"
    return out if len(out) <= 40 else out[:37] + "..."
