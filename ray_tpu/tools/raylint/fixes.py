"""raylint ``--fix`` — mechanically-safe autofixes.

Two fix classes, both chosen because the rewrite is provably
behavior-preserving:

- **suppression-syntax normalization**: any comment the suppression
  parser already accepts (``model._SUPPRESS_RE``) is rewritten to the
  canonical ``# raylint: disable=<r1>,<r2> -- reason`` spelling.  The
  parse result is identical before and after, so only the bytes
  change.
- **eager log formatting -> lazy %-args**: a hot-path logger call
  whose message is an f-string (``log.info(f"x {a!r}")``) or a
  %-interpolated string (``log.info("x %s" % a)``) becomes the lazy
  form ``log.info("x %r", a)`` / ``log.info("x %s", a)`` — the
  ``log-hygiene`` finding's suggested fix, applied only when the
  translation is exact: no format specs, no ``!a`` conversions, the
  call on a single line, and no positional args already present.

Anything outside those bounds is left alone — ``--fix`` must never
produce a diff a reviewer has to think about.  Applying the fixer to
its own output is a no-op (idempotence is tested).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .model import _SUPPRESS_RE, ProjectModel, hot_paths
from .rules import _is_logger_call

__all__ = ["compute_fixes", "apply_fixes"]


# ------------------------------------------------------------------ comments
def _normalize_suppression(line: str) -> Optional[str]:
    """Canonical spelling for a suppression comment, or None when the
    line is already canonical / is not a suppression."""
    m = _SUPPRESS_RE.search(line)
    if m is None:
        return None
    rules = ",".join(r.strip() for r in m.group(1).split(",")
                     if r.strip())
    reason = m.group("reason")
    canon = f"# raylint: disable={rules}"
    if reason is not None:
        canon += f" -- {reason.strip()}"
    prefix = line[:m.start()].rstrip()
    fixed = f"{prefix}  {canon}" if prefix else canon
    return fixed if fixed != line.rstrip("\n") else None


# ------------------------------------------------------------------ logging
def _fstring_to_lazy(
        arg: ast.JoinedStr) -> Optional[Tuple[str, List[ast.expr]]]:
    """(format string, interpolated exprs) for an exactly-translatable
    f-string, else None."""
    parts: List[str] = []
    exprs: List[ast.expr] = []
    for v in arg.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value.replace("%", "%%"))
        elif isinstance(v, ast.FormattedValue):
            if v.format_spec is not None:
                return None
            if v.conversion == ord("r"):
                parts.append("%r")
            elif v.conversion in (-1, ord("s")):
                parts.append("%s")
            else:           # !a has no %-directive twin
                return None
            exprs.append(v.value)
        else:
            return None
    if not exprs:
        return None         # placeholder-free: nothing to defer
    return "".join(parts), exprs


def _percent_to_lazy(
        arg: ast.BinOp) -> Optional[Tuple[str, List[ast.expr]]]:
    """("fmt" % args) -> (fmt, [args...]); the directives are already
    %-style so the string passes through untouched."""
    if not (isinstance(arg.op, ast.Mod)
            and isinstance(arg.left, ast.Constant)
            and isinstance(arg.left.value, str)):
        return None
    fmt = arg.left.value
    if "%(" in fmt:
        return None         # dict interpolation has no lazy-args twin
    right = arg.right
    exprs = (list(right.elts) if isinstance(right, ast.Tuple)
             else [right])
    if any(isinstance(e, ast.Starred) for e in exprs):
        return None
    return fmt, exprs


def _lazy_call_source(node: ast.Call, fmt: str,
                      exprs: List[ast.expr]) -> str:
    new = ast.Call(
        func=node.func,
        args=[ast.Constant(fmt)] + list(exprs),
        keywords=node.keywords)
    return ast.unparse(ast.fix_missing_locations(
        ast.copy_location(new, node)))


def _log_call_edits(model: ProjectModel,
                    info) -> List[Tuple[int, int, int, str]]:
    """(lineno, col_start, col_end, replacement) for every exactly
    translatable eager hot-path logger call in one module."""
    edits: List[Tuple[int, int, int, str]] = []
    for fi in model.functions.values():
        if fi.module != info.name:
            continue
        if not hot_paths.dispatch_hot(fi.name):
            continue
        for node in model.walk_own(fi.node):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if node.lineno != node.end_lineno:
                continue    # multi-line: splicing is not safe
            if len(node.args) != 1:
                continue    # extra positional args already feed %
            if _is_logger_call(node) is None:
                continue
            arg = node.args[0]
            lazy = None
            if isinstance(arg, ast.JoinedStr):
                lazy = _fstring_to_lazy(arg)
            elif isinstance(arg, ast.BinOp):
                lazy = _percent_to_lazy(arg)
            if lazy is None:
                continue
            edits.append((node.lineno, node.col_offset,
                          node.end_col_offset,
                          _lazy_call_source(node, *lazy)))
    return edits


# ------------------------------------------------------------------ driver
def compute_fixes(root: str,
                  model: Optional[ProjectModel] = None,
                  ) -> Dict[str, Tuple[str, str]]:
    """relpath -> (old_source, new_source) for every module the fixer
    would change.  Pure: nothing is written."""
    model = model or ProjectModel(root)
    out: Dict[str, Tuple[str, str]] = {}
    for name in sorted(model.modules):
        info = model.modules[name]
        lines = list(info.lines)
        changed = False

        by_line: Dict[int, List[Tuple[int, int, str]]] = {}
        for lineno, c0, c1, repl in _log_call_edits(model, info):
            by_line.setdefault(lineno, []).append((c0, c1, repl))
        for lineno, edits in by_line.items():
            text = lines[lineno - 1]
            for c0, c1, repl in sorted(edits, reverse=True):
                text = text[:c0] + repl + text[c1:]
            if text != lines[lineno - 1]:
                lines[lineno - 1] = text
                changed = True

        for i, text in enumerate(lines):
            fixed = _normalize_suppression(text)
            if fixed is not None:
                lines[i] = fixed
                changed = True

        if changed:
            old = "\n".join(info.lines) + "\n"
            new = "\n".join(lines) + "\n"
            if new != old:
                out[info.relpath] = (old, new)
    return out


def apply_fixes(root: str,
                model: Optional[ProjectModel] = None) -> List[str]:
    """Write the fixes to disk; returns the changed relpaths."""
    import os

    project_dir = os.path.dirname(os.path.abspath(root)) or "."
    changed = compute_fixes(root, model)
    for relpath, (_old, new) in sorted(changed.items()):
        path = os.path.join(project_dir, relpath)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(new)
    return sorted(changed)
