"""raylint rules: the framework's distributed-runtime invariants.

Each rule is a function ``rule(model) -> List[Finding]`` registered in
``RULES``.  Findings anchor at a source line; a
``# raylint: disable=<rule> -- reason`` comment on that line (or a
comment-only line directly above) suppresses them.  Messages are kept
line-number-free so baseline fingerprints survive unrelated edits.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .model import (FuncInfo, ModuleInfo, ProjectModel, call_desc,
                    hot_paths, jit_build_desc, lvalue_key,
                    _short_fn, _short_key)
from .protocol import FT_TYPED_ERRORS, ProtocolIndex

# --------------------------------------------------------------------------
# findings
# --------------------------------------------------------------------------

@dataclass
class Finding:
    rule: str
    path: str          # project-root relative
    line: int
    symbol: str        # enclosing function/class qualname (or module)
    message: str
    baselined: bool = False

    @property
    def fingerprint(self) -> str:
        # line numbers deliberately excluded: a baseline entry must
        # survive unrelated edits shifting the file
        return f"{self.rule}::{self.path}::{self.symbol}::{self.message}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.symbol}: {self.message}")

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "baselined": self.baselined,
                "fingerprint": self.fingerprint}


def _suppressed(info: ModuleInfo, rule: str, line: int) -> bool:
    for s in info.suppressions:
        if s.reason is None:
            continue  # reasonless disables are invalid (see rule below)
        if rule not in s.rules and "all" not in s.rules:
            continue
        if s.line == line or (s.comment_only and s.line == line - 1):
            return True
    return False


class _Collector:
    def __init__(self, model: ProjectModel, rule: str):
        self.model = model
        self.rule = rule
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[str, int, str]] = set()

    def add(self, info: ModuleInfo, line: int, symbol: str,
            message: str) -> None:
        if _suppressed(info, self.rule, line):
            return
        key = (info.relpath, line, message)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(
            rule=self.rule, path=info.relpath, line=line,
            symbol=symbol, message=message))


# --------------------------------------------------------------------------
# shared helpers
# --------------------------------------------------------------------------

_RPC_BLOCKING_ATTRS = {"call", "call_with_retry", "call_retry",
                       "call_idempotent"}
_SOCKET_BLOCKING_ATTRS = {"recv", "recv_into", "accept"}
# attr calls that block FOREVER unless given a timeout argument
_NEEDS_TIMEOUT_ATTRS = {"result", "wait", "join", "acquire", "get"}


def _has_timeout(call: ast.Call) -> bool:
    if call.args:
        return True  # positional timeout (result(t), wait(t), get(block,t))
    return any(kw.arg in ("timeout", "block", "blocking", "timeout_s")
               for kw in call.keywords)


def _blocking_desc(info: ModuleInfo, call: ast.Call) -> Optional[str]:
    """Classify a call site as a DIRECT blocking operation, or None.
    RPC calls count even when bounded by a timeout (a bounded stall
    under a lock still wedges every other holder for the duration);
    generic waits count only when unbounded."""
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr in _RPC_BLOCKING_ATTRS:
            return f"rpc {call_desc(call)}(...)"
        if f.attr == "sleep" and isinstance(f.value, ast.Name) and \
                info.imports.get(f.value.id, f.value.id) == "time":
            return "time.sleep(...)"
        if f.attr in _SOCKET_BLOCKING_ATTRS:
            return f"socket {call_desc(call)}(...)"
        if f.attr == "create_connection" and not _has_timeout(call):
            return f"socket {call_desc(call)}(...) without timeout"
        if f.attr in _NEEDS_TIMEOUT_ATTRS and not _has_timeout(call):
            if f.attr == "get" and call.keywords:
                return None  # dict-style .get(default=...) etc.
            return f"un-timeouted {call_desc(call)}()"
    elif isinstance(f, ast.Name):
        if f.id == "retry_call":
            return "rpc retry_call(...)"
        if f.id == "sleep" and info.imports.get(f.id, "") == "time.sleep":
            return "time.sleep(...)"
    return None


def _expr_eq(a: ast.AST, b: ast.AST) -> bool:
    try:
        return ast.dump(a) == ast.dump(b)
    except Exception:
        return False


def _walk_region(stmts: Sequence[ast.stmt]):
    """Walk statements without descending into nested defs/lambdas
    (their bodies run elsewhere; calls TO them resolve via the graph)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------------
# rule: blocking-under-lock
# --------------------------------------------------------------------------

_TRANSITIVE_DEPTH = 4


def _blocking_summary(model: ProjectModel,
                      memo: Dict[Tuple[str, int],
                                 Optional[List[str]]],
                      qn: str, depth: int) -> Optional[List[str]]:
    """A call chain from ``qn`` to a direct blocking op (as printable
    hops), or None.  Depth-limited and memoized BY (qn, depth): a
    None computed with the budget nearly exhausted must not shadow a
    full-depth query from another lock region (that would silently
    drop real deadlock findings)."""
    key = (qn, depth)
    if key in memo:
        return memo[key]
    memo[key] = None
    fi = model.functions.get(qn)
    if fi is None:
        return None
    info = model.modules[fi.module]
    for node in model.walk_own(fi.node):
        if isinstance(node, ast.Call):
            desc = _blocking_desc(info, node)
            if desc is not None:
                memo[key] = [f"{desc} at {info.relpath}"]
                return memo[key]
    if depth <= 0:
        return None
    for callee, _line, via in model.calls.get(qn, ()):
        if callee == qn:
            continue
        chain = _blocking_summary(model, memo, callee, depth - 1)
        if chain is not None:
            memo[key] = [f"{via}()"] + chain
            return memo[key]
    return None


def rule_blocking_under_lock(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "blocking-under-lock")
    memo: Dict[Tuple[str, int], Optional[List[str]]] = {}
    for fi in model.functions.values():
        info = model.modules[fi.module]
        for node in model.walk_own(fi.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                lock = model.lock_context(info, fi, item.context_expr)
                if lock is None:
                    continue
                _scan_lock_region(model, out, memo, info, fi,
                                  lock, item.context_expr, node.body)
                break  # one finding set per with-statement
    return out.findings


def _scan_lock_region(model: ProjectModel, out: _Collector, memo,
                      info: ModuleInfo, fi: FuncInfo,
                      lock: Tuple[str, bool], lock_expr: ast.AST,
                      body: Sequence[ast.stmt]) -> None:
    lock_name, is_cond = lock
    for node in _walk_region(body):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # a Condition's own wait() RELEASES the lock while waiting:
        # that is the one legitimate blocking call inside its region
        if is_cond and isinstance(f, ast.Attribute) and \
                f.attr == "wait" and _expr_eq(f.value, lock_expr):
            continue
        desc = _blocking_desc(info, node)
        if desc is not None:
            out.add(info, node.lineno, fi.qualname,
                    f"{desc} while holding {lock_name!r}")
            continue
        target = model._resolve_call(info, fi, node)
        if target is None:
            continue
        chain = _blocking_summary(model, memo, target,
                                  _TRANSITIVE_DEPTH)
        if chain is not None:
            path = " -> ".join([f"{call_desc(node)}()"] + chain)
            out.add(info, node.lineno, fi.qualname,
                    f"call reaches a blocking op while holding "
                    f"{lock_name!r}: {path}")


# --------------------------------------------------------------------------
# rule: handler-idempotency
# --------------------------------------------------------------------------

_MUTATING_HANDLER_RE = re.compile(
    r"^(register|remove|create|drain|kill)_|(_put|_del)$")
_IDEM_WRAPPERS = {"_mut", "idempotent_handler"}


def _is_wrapped(value: ast.AST) -> bool:
    if isinstance(value, ast.Call):
        f = value.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        return name in _IDEM_WRAPPERS
    return False


def rule_handler_idempotency(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "handler-idempotency")
    for fi in model.functions.values():
        info = model.modules[fi.module]
        for node in model.walk_own(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name == "RpcServer" and node.args and \
                    isinstance(node.args[0], ast.Dict):
                table = node.args[0]
                for key, value in zip(table.keys, table.values):
                    if not (isinstance(key, ast.Constant)
                            and isinstance(key.value, str)):
                        continue
                    hname = key.value
                    if _MUTATING_HANDLER_RE.search(hname) and \
                            not _is_wrapped(value):
                        out.add(info, key.lineno, fi.qualname,
                                f"mutating handler {hname!r} "
                                f"registered without _mut/"
                                f"idempotent_handler")
            elif name == "add_handler" and len(node.args) >= 2 and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                hname = node.args[0].value
                if _MUTATING_HANDLER_RE.search(hname) and \
                        not _is_wrapped(node.args[1]):
                    out.add(info, node.lineno, fi.qualname,
                            f"mutating handler {hname!r} added "
                            f"without _mut/idempotent_handler")
    return out.findings


# --------------------------------------------------------------------------
# rule: trace-propagation
# --------------------------------------------------------------------------

# driver-side ROOT operations that must mint a span (module suffix,
# function name) — the entry points of PR-3's tracing plane
_ROOT_OPS = (
    ("dag.compiled", "execute"),
    ("serve.handle", "remote"),
    ("train.cross_pipeline", "train_step"),
)
_BUNDLE_MARKER_KEYS = {"owner"}
_BUNDLE_PAYLOAD_KEYS = {"args", "function", "method", "actor_id"}


def _uses_span(model: ProjectModel, fi: FuncInfo, depth: int = 1) -> bool:
    info = model.modules[fi.module]
    for node in model.walk_own(fi.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "span":
            return True
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                info.imports.get(node.func.id, "").endswith(
                    "tracing.span"):
            return True
    if depth > 0:
        for callee, _l, _v in model.calls.get(fi.qualname, ()):
            sub = model.functions.get(callee)
            if sub is not None and _uses_span(model, sub, depth - 1):
                return True
    return False


def rule_trace_propagation(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "trace-propagation")
    # (a) task/actor wire bundles must carry the trace context
    for fi in model.functions.values():
        info = model.modules[fi.module]
        for node in model.walk_own(fi.node):
            if isinstance(node, ast.Dict):
                keys = {k.value for k in node.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
                if _BUNDLE_MARKER_KEYS <= keys and \
                        keys & _BUNDLE_PAYLOAD_KEYS and \
                        "trace" not in keys:
                    out.add(info, node.lineno, fi.qualname,
                            "task bundle ships without a 'trace' "
                            "field (context lost across the hop)")
        # (b) a 'trace' parameter that is never read is dropped context
        fnode = fi.node
        argnames = {a.arg for a in (
            list(fnode.args.posonlyargs) + list(fnode.args.args) +
            list(fnode.args.kwonlyargs))}
        for tname in ("trace", "trace_ctx"):
            if tname not in argnames:
                continue
            # Full walk (NOT walk_own): a closure/callback capturing
            # the trace param IS propagation — the common call_async
            # callback shape must not be flagged.
            used = any(isinstance(n, ast.Name) and n.id == tname
                       for n in ast.walk(fnode)
                       if n is not fnode)
            if not used:
                out.add(info, fnode.lineno, fi.qualname,
                        f"parameter {tname!r} accepted but never "
                        f"propagated (scope_from / envelope)")
    # (c) root ops must mint a driver-side span
    for suffix, fname in _ROOT_OPS:
        for qn in model.by_name.get(fname, ()):
            fi = model.functions[qn]
            if not fi.module.endswith(suffix):
                continue
            if not _uses_span(model, fi):
                info = model.modules[fi.module]
                out.add(info, fi.line, fi.qualname,
                        f"driver-side root op {fname!r} does not mint "
                        f"a tracing span")
    return out.findings


# --------------------------------------------------------------------------
# rule: ft-exception-swallow
# --------------------------------------------------------------------------

_FT_TYPES = {"ActorError", "ActorDiedError", "ActorUnavailableError",
             "ChannelError", "ObjectLostError", "OwnerDiedError",
             "RayTpuError", "TaskError"}
# calls in a try body that can surface FT errors (RPC results re-raise
# server-shipped exceptions; channel reads raise typed FT errors)
_FT_CAPABLE_ATTRS = {"call", "call_async", "call_with_retry",
                     "call_retry", "call_idempotent", "result",
                     "get_value", "put_value", "wait_and_get",
                     "submit_task", "submit_actor_task", "get_buffer",
                     "finish"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _catches_ft(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names: List[str] = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Attribute):
        names = [t.attr]
    return bool(set(names) & _FT_TYPES)


def _silently_swallows(handler: ast.ExceptHandler) -> bool:
    """No re-raise, no logging/cleanup call, exception object unused:
    the failure vanishes."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call) and node is not handler.type:
            return False  # logging / cleanup / error-storing call
        if handler.name and isinstance(node, ast.Name) and \
                node.id == handler.name:
            return False  # the error object is USED somehow
    return True


def rule_ft_exception_swallow(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "ft-exception-swallow")
    for fi in model.functions.values():
        info = model.modules[fi.module]
        for node in model.walk_own(fi.node):
            if not isinstance(node, ast.Try):
                continue
            ft_capable = any(
                isinstance(c, ast.Call)
                and ((isinstance(c.func, ast.Attribute)
                      and c.func.attr in _FT_CAPABLE_ATTRS)
                     or (isinstance(c.func, ast.Name)
                         and c.func.id == "retry_call"))
                for c in _walk_region(node.body))
            if not ft_capable:
                continue
            ft_handled_earlier = False
            for handler in node.handlers:
                if _catches_ft(handler):
                    ft_handled_earlier = True
                    continue
                if not _is_broad(handler):
                    continue
                if ft_handled_earlier:
                    continue  # FT types peeled off by a prior clause
                if _silently_swallows(handler):
                    out.add(info, handler.lineno, fi.qualname,
                            "broad except silently swallows a call "
                            "that can raise FT errors (ActorError/"
                            "ChannelError/ObjectLostError)")
    return out.findings


# --------------------------------------------------------------------------
# rule: resource-teardown
# --------------------------------------------------------------------------

_RESOURCE_NAMES = {"RpcServer", "RpcClient", "ReconnectingClient",
                   "ObjectStreamServer", "Channel", "ClientPool",
                   "EventShipper"}
_RESOURCE_ATTR_CALLS = {("socket", "socket"),
                        ("socket", "create_connection"),
                        ("_socket", "socket"),
                        ("_socket", "create_connection")}
_TEARDOWN_VERBS = {"close", "close_all", "shutdown", "destroy",
                   "detach", "disconnect", "stop", "terminate",
                   "abort", "unlink", "release", "kill", "join"}


def _resource_ctor(info: ModuleInfo, call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in _RESOURCE_NAMES:
            return f.id
        if f.id == "open":
            return "open"
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if (f.value.id, f.attr) in _RESOURCE_ATTR_CALLS:
            return f"{f.value.id}.{f.attr}"
        if f.attr in _RESOURCE_NAMES and f.attr == "Channel":
            return "Channel"
    return None


def _class_tears_down(model: ProjectModel, fi: FuncInfo,
                      attr: str) -> bool:
    """Does some teardown-verb method of the class reference self.attr?"""
    if fi.cls is None:
        return False
    ci = model.classes.get(f"{fi.module}:{fi.cls}")
    if ci is None:
        return False
    for mname, mqn in ci.methods.items():
        if mname not in _TEARDOWN_VERBS and \
                not mname.startswith(("close", "shutdown", "stop",
                                      "disconnect", "tear", "__exit__",
                                      "__del__")):
            continue
        mnode = model.functions[mqn].node
        for node in ast.walk(mnode):
            if isinstance(node, ast.Attribute) and node.attr == attr \
                    and isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return True
    return False


def _local_released(model: ProjectModel, fi: FuncInfo,
                    name: str, after_line: int) -> bool:
    """Within the function: is local ``name`` closed on some path, or
    does it escape (returned / yielded / stored / passed along)?"""
    for node in model.walk_own(fi.node):
        line = getattr(node, "lineno", 0)
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in _TEARDOWN_VERBS and \
                    isinstance(f.value, ast.Name) and f.value.id == name:
                return True
            # passed as a (possibly nested) argument -> escapes
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                if any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(arg)):
                    if line >= after_line:
                        return True
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = node.value
            if v is not None and any(
                    isinstance(n, ast.Name) and n.id == name
                    for n in ast.walk(v)):
                return True
        elif isinstance(node, ast.Assign) and line > after_line:
            # stored into an attribute / container -> escapes
            if any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(node.value)) and any(
                    not isinstance(t, ast.Name) for t in node.targets):
                return True
    return False


def rule_resource_teardown(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "resource-teardown")
    for fi in model.functions.values():
        info = model.modules[fi.module]
        with_ctx_calls: Set[int] = set()
        for node in model.walk_own(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        with_ctx_calls.add(id(item.context_expr))
        for node in model.walk_own(fi.node):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            res = _resource_ctor(info, node.value)
            if res is None or id(node.value) in with_ctx_calls:
                continue
            if len(node.targets) != 1:
                continue
            target = node.targets[0]
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                if not _class_tears_down(model, fi, target.attr):
                    out.add(info, node.lineno, fi.qualname,
                            f"{res} stored on self.{target.attr} but "
                            f"no teardown method of the class "
                            f"closes it")
            elif isinstance(target, ast.Name):
                if not _local_released(model, fi, target.id,
                                       node.lineno):
                    out.add(info, node.lineno, fi.qualname,
                            f"{res} bound to local {target.id!r} is "
                            f"neither closed nor escapes this "
                            f"function")
    return out.findings


# --------------------------------------------------------------------------
# rule: thread-hygiene
# --------------------------------------------------------------------------

def _is_thread_ctor(info: ModuleInfo, call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread" and \
            isinstance(f.value, ast.Name) and \
            info.imports.get(f.value.id, f.value.id) == "threading":
        return True
    return isinstance(f, ast.Name) and f.id == "Thread" and \
        info.imports.get("Thread", "") == "threading.Thread"


def _attr_joined(model: ProjectModel, fi: FuncInfo, attr: str) -> bool:
    if fi.cls is None:
        return False
    ci = model.classes.get(f"{fi.module}:{fi.cls}")
    if ci is None:
        return False
    for mqn in ci.methods.values():
        mnode = model.functions[mqn].node
        has_join = False
        aliases_attr = False
        for node in ast.walk(mnode):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                recv = node.func.value
                if isinstance(recv, ast.Attribute) and \
                        recv.attr == attr and \
                        isinstance(recv.value, ast.Name) and \
                        recv.value.id == "self":
                    return True
                has_join = True
            # defensive alias: t = getattr(self, "<attr>", None)
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "getattr" and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) and \
                    node.args[1].value == attr:
                aliases_attr = True
        if has_join and aliases_attr:
            return True
    return False


def _local_name_joined(model: ProjectModel, fi: FuncInfo,
                       name: str) -> bool:
    """``name.join(...)`` anywhere in the same function."""
    for node in model.walk_own(fi.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == name:
            return True
    return False


def rule_thread_hygiene(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "thread-hygiene")
    for fi in model.functions.values():
        info = model.modules[fi.module]
        # bind each ctor call to its assignment target (if any) first,
        # so the bare-Call walk below doesn't re-report assigned ones
        assigned: Dict[int, Optional[ast.AST]] = {}
        for node in model.walk_own(fi.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _is_thread_ctor(info, node.value):
                assigned[id(node.value)] = node.targets[0] \
                    if len(node.targets) == 1 else None
        for node in model.walk_own(fi.node):
            if not (isinstance(node, ast.Call)
                    and _is_thread_ctor(info, node)):
                continue
            ctor = node
            target = assigned.get(id(node))
            # daemon must be TRUTHY: an explicit daemon=False is the
            # same interpreter-exit blocker as no daemon at all.  A
            # non-constant expression is assumed intentional.
            daemon_true = any(
                kw.arg == "daemon"
                and (not isinstance(kw.value, ast.Constant)
                     or bool(kw.value.value))
                for kw in ctor.keywords)
            if not daemon_true:
                # A non-daemon thread is fine IF some path joins it.
                if isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self" and \
                        _attr_joined(model, fi, target.attr):
                    continue
                if isinstance(target, ast.Name) and \
                        _local_name_joined(model, fi, target.id):
                    continue
                out.add(info, ctor.lineno, fi.qualname,
                        "threading.Thread without daemon=True or a "
                        "join (a non-daemon leak blocks interpreter "
                        "exit)")
                continue
            # stored on self => long-lived: teardown must join it
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                if not _attr_joined(model, fi, target.attr):
                    out.add(info, ctor.lineno, fi.qualname,
                            f"long-lived thread self.{target.attr} "
                            f"has no join on any teardown path")
    return out.findings


# --------------------------------------------------------------------------
# rule: unbounded-mailbox
# --------------------------------------------------------------------------

# Method names that sit on an RPC/dispatch/ingest path: growth there is
# driven by EXTERNAL demand, so an unbounded queue is the OOM-under-
# overload failure class the admission-control plane exists to close.
# Tokens are word-bounded on "_" so e.g. "compute"/"output" don't match
# "put"; "on" matches only as an `on_*` hook prefix (a trailing "..._on"
# is prose, not an event handler).
_GROW_PATH_RE = re.compile(
    r"(?:^|_)(submit|dispatch|enqueue|push|send|put|call|request|recv|"
    r"handle|deliver|ship|ingest|accept)(?:_|$)|(?:^|_)on_", re.I)
# Names whose appearance in a comparison reads as a capacity check.
_BOUND_NAME_RE = re.compile(
    r"(max|cap$|capacity|limit|bound|high_water|quota)", re.I)
# Raising one of these inside the method IS the bound check's teeth.
_REJECT_EXC_RE = re.compile(
    r"(BackPressure|LimitExceeded|Overflow|Full)")


def _unbounded_mailbox_ctor(info: ModuleInfo,
                            value: ast.AST) -> Optional[str]:
    """``queue.Queue()`` with no maxsize / ``deque()`` with no maxlen /
    a bare ``[]`` — the unbounded mailbox shapes; else None."""
    if isinstance(value, ast.List) and not value.elts:
        return "[]"
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    qname = ""
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        qname = f"{info.imports.get(f.value.id, f.value.id)}.{f.attr}"
    elif isinstance(f, ast.Name):
        qname = info.imports.get(f.id, f.id)
    if qname in ("queue.Queue", "queue.LifoQueue",
                 "queue.PriorityQueue", "queue.SimpleQueue"):
        bounded = bool(value.args) or any(
            kw.arg == "maxsize" for kw in value.keywords)
        return None if bounded else "queue.Queue()"
    if qname in ("collections.deque", "deque"):
        bounded = len(value.args) >= 2 or any(
            kw.arg == "maxlen" for kw in value.keywords)
        return None if bounded else "deque()"
    return None


def _has_bound_check(model: ProjectModel, fi: FuncInfo) -> bool:
    """A comparison over len()/qsize()/a capacity-named value, or a
    typed rejection raise, anywhere in the method."""
    for node in model.walk_own(fi.node):
        if isinstance(node, ast.Compare):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    cf = sub.func
                    cname = cf.id if isinstance(cf, ast.Name) else \
                        getattr(cf, "attr", "")
                    if cname in ("len", "qsize"):
                        return True
                if isinstance(sub, ast.Attribute) and \
                        _BOUND_NAME_RE.search(sub.attr):
                    return True
                if isinstance(sub, ast.Name) and \
                        _BOUND_NAME_RE.search(sub.id):
                    return True
        elif isinstance(node, ast.Raise) and node.exc is not None:
            exc = node.exc
            f = exc.func if isinstance(exc, ast.Call) else exc
            ename = f.id if isinstance(f, ast.Name) else \
                getattr(f, "attr", "")
            if ename and _REJECT_EXC_RE.search(ename):
                return True
    return False


def rule_unbounded_mailbox(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "unbounded-mailbox")
    for ci in model.classes.values():
        info = model.modules[ci.module]
        # 1) self-stored unbounded mailbox attributes, assigned
        #    anywhere in the class body.
        mailboxes: Dict[str, str] = {}
        for sub in ast.walk(ci.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                t, v = sub.targets[0], sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                t, v = sub.target, sub.value  # self._q: Queue = Queue()
            else:
                continue
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self":
                kind = _unbounded_mailbox_ctor(info, v)
                if kind is not None:
                    mailboxes[t.attr] = kind
        if not mailboxes:
            continue
        # 2) growth sites (put/append) on dispatch-path methods with no
        #    bound check in the same method.
        for mname, mqn in ci.methods.items():
            if not _GROW_PATH_RE.search(mname):
                continue
            fi = model.functions[mqn]
            grows = []
            for node in model.walk_own(fi.node):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("put", "put_nowait", "append",
                                           "appendleft"):
                    recv = node.func.value
                    if isinstance(recv, ast.Attribute) and \
                            isinstance(recv.value, ast.Name) and \
                            recv.value.id == "self" and \
                            recv.attr in mailboxes:
                        grows.append((node, recv.attr))
            if not grows or _has_bound_check(model, fi):
                continue
            for node, attr in grows:
                out.add(info, node.lineno, fi.qualname,
                        f"self.{attr} ({mailboxes[attr]}) grows on "
                        f"dispatch-path method {mname!r} with no bound "
                        f"check — unbounded mailbox")
    return out.findings


# --------------------------------------------------------------------------
# rule: log-hygiene
# --------------------------------------------------------------------------

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}
# Receivers that read as loggers ("logger", "_log", "_access_log", ...)
_LOGGER_NAME_RE = re.compile(r"(^|_)log(ger)?s?($|_)|logger", re.I)
# Hot/dispatch-path classification lives in model.hot_paths — ONE
# token table shared with jit-in-hot-path and the device-plane rules.
# Modules where bare print() IS the interface (CLI entry points).
_PRINT_OK_MODULE_RE = re.compile(
    r"(^|\.)((scripts|tools)(\.|$)|__main__$|worker_main$|bench)")


def _is_logger_call(call: ast.Call) -> Optional[str]:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in _LOG_METHODS):
        return None
    recv = f.value
    name = ""
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    elif isinstance(recv, ast.Call):
        cf = recv.func
        cname = cf.attr if isinstance(cf, ast.Attribute) else \
            getattr(cf, "id", "")
        if cname == "getLogger":
            return f"getLogger(...).{f.attr}"
    if name and _LOGGER_NAME_RE.search(name):
        return f"{name}.{f.attr}"
    return None


def _eager_format_kind(arg: ast.AST) -> Optional[str]:
    """How the message argument is PRE-formatted (paid even when the
    level is disabled), or None when it is lazy."""
    if isinstance(arg, ast.JoinedStr):
        return "f-string"
    if isinstance(arg, ast.Call) and \
            isinstance(arg.func, ast.Attribute) and \
            arg.func.attr == "format":
        return ".format(...)"
    if isinstance(arg, ast.BinOp):
        if isinstance(arg.op, ast.Mod):
            return "'%'-interpolated string"
        if isinstance(arg.op, ast.Add):
            for side in (arg.left, arg.right):
                if isinstance(side, ast.Constant) and \
                        isinstance(side.value, str):
                    return "string concatenation"
    return None


def rule_log_hygiene(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "log-hygiene")
    for fi in model.functions.values():
        info = model.modules[fi.module]
        on_hot_path = hot_paths.dispatch_hot(fi.name)
        print_ok = (_PRINT_OK_MODULE_RE.search(info.name) is not None
                    or fi.name == "main")
        for node in model.walk_own(fi.node):
            if not isinstance(node, ast.Call):
                continue
            # (a) bare print() in runtime modules: output that bypasses
            # the structured plane entirely (no level, no trace stamp,
            # no shipping) — CLI entry points are the one legit home.
            if isinstance(node.func, ast.Name) and \
                    node.func.id == "print" and not print_ok:
                out.add(info, node.lineno, fi.qualname,
                        "bare print() in a runtime module — use a "
                        "logger (records get trace-stamped and "
                        "shipped) or move output to the CLI layer")
                continue
            # (b) eager formatting in logger calls on hot paths: the
            # formatting cost is paid per call even with the level
            # off; %-style args defer it to the handler.
            if not on_hot_path or not node.args:
                continue
            desc = _is_logger_call(node)
            if desc is None:
                continue
            kind = _eager_format_kind(node.args[0])
            if kind is not None:
                out.add(info, node.lineno, fi.qualname,
                        f"{desc}({kind}) on hot-path method "
                        f"{fi.name!r} pre-formats its message — pass "
                        f"lazy %-style args instead")
    return out.findings


# --------------------------------------------------------------------------
# rule: metric-cardinality
# --------------------------------------------------------------------------

_METRIC_TAG_METHODS = {"inc", "set", "observe"}
# Identifier names that mint per-operation in this codebase: a tag
# value carrying one creates a new metric series per op — the registry,
# the exposition page, and the head TSDB all grow without bound.
_UNBOUNDED_ID_RE = re.compile(
    r"(?:^|_)(trace|span|task|object|obj|request|req|session|job)"
    r"_?id$|^(oid|uuid|idem_key)$")


def _unbounded_tag_reason(expr: ast.AST) -> Optional[str]:
    """Why this tag-value expression is an unbounded identifier, or
    None when it looks bounded (node names, kind/where enums, ...)."""
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Attribute) and f.attr == "hex":
            return "a .hex() identity rendering"
        callee = (f.attr if isinstance(f, ast.Attribute)
                  else getattr(f, "id", ""))
        if callee in ("uuid1", "uuid4", "token_hex"):
            return f"a fresh {callee}()"
        if callee == "str" and expr.args:
            return _unbounded_tag_reason(expr.args[0])
        return None
    if isinstance(expr, (ast.Name, ast.Attribute)):
        name = expr.id if isinstance(expr, ast.Name) else expr.attr
        if _UNBOUNDED_ID_RE.search(name):
            return f"identifier {name!r}"
        return None
    if isinstance(expr, ast.Subscript):
        # spec["trace_id"] names the id in the key; task_id[:8]
        # (a truncated id is still 16^8 values) recurses on the value.
        sl = expr.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str) \
                and _UNBOUNDED_ID_RE.search(sl.value):
            return f"identifier {sl.value!r}"
        return _unbounded_tag_reason(expr.value)
    if isinstance(expr, ast.JoinedStr):
        for part in expr.values:
            if isinstance(part, ast.FormattedValue):
                reason = _unbounded_tag_reason(part.value)
                if reason is not None:
                    return reason
        return None
    if isinstance(expr, ast.BinOp):
        for side in (expr.left, expr.right):
            reason = _unbounded_tag_reason(side)
            if reason is not None:
                return reason
    return None


def rule_metric_cardinality(model: ProjectModel) -> List[Finding]:
    """Instrumentation sites feeding unbounded identifiers (object/
    trace/task/request ids, uuids, .hex() renderings) into metric tag
    values.  Metrics aggregate; ids enumerate — an id-valued tag turns
    a bounded series family into one series per operation, growing
    every process registry, the /metrics exposition, and the head
    TSDB until the cardinality cap starts dropping REAL series."""
    out = _Collector(model, "metric-cardinality")
    for fi in model.functions.values():
        info = model.modules[fi.module]
        for node in model.walk_own(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _METRIC_TAG_METHODS):
                continue
            tags = None
            for kw in node.keywords:
                if kw.arg == "tags":
                    tags = kw.value
            if tags is None and len(node.args) >= 2:
                tags = node.args[1]  # inc/set/observe(value, tags)
            if not isinstance(tags, ast.Dict):
                continue
            for key, value in zip(tags.keys, tags.values):
                reason = _unbounded_tag_reason(value)
                if reason is None:
                    continue
                label = (repr(key.value)
                         if isinstance(key, ast.Constant)
                         else "<dynamic>")
                out.add(info, node.lineno, fi.qualname,
                        f"metric tag {label} feeds {reason} — "
                        f"per-operation ids explode series "
                        f"cardinality (one series per id); use a "
                        f"bounded label or drop the tag")
    return out.findings


# --------------------------------------------------------------------------
# rule: jit-in-hot-path
# --------------------------------------------------------------------------

# Method names that run per dispatch / per step / per request: a
# jax.jit/pjit wrapper built THERE is built per call — each wrapper
# owns a fresh compile cache, so every invocation re-traces and
# recompiles (the xla-recompile-storm alert's favorite root cause).
# Classification (device-hot tokens, builder exemption) lives in
# model.hot_paths; jit-build detection in model.jit_build_desc — both
# shared with the device-plane dataflow rules.
_jit_call_desc = jit_build_desc


def _none_guard_target(test: ast.AST) -> Optional[ast.AST]:
    """The expression a ``if X is None: `` / ``if not X:`` test
    guards, or None — the build-once cache idiom's gate."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.ops[0], ast.Is) \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        return test.left
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return test.operand
    return None


_lvalue_key = lvalue_key


def rule_jit_in_hot_path(model: ProjectModel) -> List[Finding]:
    """``jax.jit``/``pjit`` invoked inside dispatch/step/per-request
    methods: the wrapper (and its compile cache) is rebuilt per call,
    so every invocation pays a retrace + XLA compile — latency spikes
    and a recompilation storm under load.  The build-once idioms stay
    clean: builder-named functions, and the ``if self._f is None:
    self._f = jax.jit(...)`` cached-guard pattern."""
    out = _Collector(model, "jit-in-hot-path")
    for fi in model.functions.values():
        if not hot_paths.device_hot(fi.name):
            continue
        info = model.modules[fi.module]

        def walk(node, guarded):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # nested defs execute elsewhere
                g = guarded
                if isinstance(child, ast.If):
                    target = _none_guard_target(child.test)
                    key = (_lvalue_key(target)
                           if target is not None else None)
                    if key is not None:
                        g = guarded | {key}
                if isinstance(child, ast.Assign) \
                        and len(child.targets) == 1 \
                        and _lvalue_key(child.targets[0]) in g:
                    # Filling the None-guarded cache: build-once.
                    continue
                if isinstance(child, ast.Call):
                    desc = _jit_call_desc(info, child)
                    if desc is not None:
                        out.add(
                            info, child.lineno, fi.qualname,
                            f"{desc}(...) inside hot-path method "
                            f"{fi.name!r} builds a fresh jit wrapper "
                            f"(own compile cache) per call — every "
                            f"invocation re-traces and recompiles; "
                            f"build it once at init or cache it "
                            f"behind a None guard")
                walk(child, g)

        walk(fi.node, frozenset())
    return out.findings


# --------------------------------------------------------------------------
# rule: suppression-syntax (meta): disables must carry a reason and
# name real rules — a typo'd disable that silently fails to suppress
# (or a reasonless one) is itself a finding
# --------------------------------------------------------------------------

def rule_suppression_syntax(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "suppression-syntax")
    known = set(RULES) | {"all"}
    for info in model.modules.values():
        for s in info.suppressions:
            if s.reason is None:
                out.add(info, s.line, info.name,
                        "raylint disable without a '-- reason' "
                        "(suppression ignored)")
            for r in s.rules - known:
                out.add(info, s.line, info.name,
                        f"raylint disable names unknown rule {r!r}")
    return out.findings


# --------------------------------------------------------------------------
# rule: journaled-mutation
# --------------------------------------------------------------------------

# The head's durable tables (cluster/head.py): any RPC handler that
# writes one must ride the _mut wrapper, which journals + fsyncs the
# redo records BEFORE the reply ships.  An unwrapped writer acks
# mutations that a head kill -9 silently loses.
_DURABLE_TABLES = {"_kv", "_actors", "_named", "_pgs"}
_TABLE_WRITE_METHODS = {"put", "pop", "clear", "replace_all",
                        "setdefault", "update"}
_JOURNAL_TRANSITIVE_DEPTH = 3


def _durable_attr(expr: ast.AST) -> Optional[str]:
    """'self._kv' -> '_kv' when it names a durable table."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id == "self" and expr.attr in _DURABLE_TABLES:
        return expr.attr
    return None


def _durable_write_in(model: ProjectModel, fi: FuncInfo,
                      depth: int = _JOURNAL_TRANSITIVE_DEPTH,
                      seen: Optional[set] = None) -> Optional[str]:
    """Name of the durable table ``fi`` writes — directly
    (``self._kv[...] = v``, ``del self._kv[...]``, ``self._kv.put/
    pop/...``) or through self-method calls up to ``depth`` — else
    None."""
    seen = set() if seen is None else seen
    if fi.qualname in seen:
        return None
    seen.add(fi.qualname)
    for node in model.walk_own(fi.node):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = node.targets
        for t in targets:
            if isinstance(t, ast.Subscript):
                hit = _durable_attr(t.value)
                if hit:
                    return hit
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _TABLE_WRITE_METHODS:
            hit = _durable_attr(node.func.value)
            if hit:
                return hit
    if depth <= 0:
        return None
    prefix = fi.qualname.rsplit(".", 1)[0]
    for node in model.walk_own(fi.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self":
            sub = model.functions.get(f"{prefix}.{node.func.attr}")
            if sub is not None:
                hit = _durable_write_in(model, sub, depth - 1, seen)
                if hit:
                    return hit
    return None


def _journal_call_in(model: ProjectModel, fi: FuncInfo,
                     depth: int = _JOURNAL_TRANSITIVE_DEPTH,
                     seen: Optional[set] = None) -> bool:
    """Does ``fi`` (or a self-method callee up to ``depth``) call
    ``self._journal(...)`` or apply through the replay path
    (``self._apply_record``)?  The replication-visibility check: only
    journaled writes ship to the standby."""
    seen = set() if seen is None else seen
    if fi.qualname in seen:
        return False
    seen.add(fi.qualname)
    prefix = fi.qualname.rsplit(".", 1)[0]
    for node in model.walk_own(fi.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self":
            if node.func.attr in ("_journal", "_apply_record"):
                return True
            if depth > 0:
                sub = model.functions.get(
                    f"{prefix}.{node.func.attr}")
                if sub is not None and \
                        _journal_call_in(model, sub, depth - 1, seen):
                    return True
    return False


def rule_journaled_mutation(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "journaled-mutation")
    for fi in model.functions.values():
        info = model.modules[fi.module]
        prefix = fi.qualname.rsplit(".", 1)[0]
        for node in model.walk_own(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            entries: List[Tuple[str, ast.AST, int]] = []
            if name == "RpcServer" and node.args and \
                    isinstance(node.args[0], ast.Dict):
                for key, value in zip(node.args[0].keys,
                                      node.args[0].values):
                    if isinstance(key, ast.Constant) and \
                            isinstance(key.value, str):
                        entries.append((key.value, value, key.lineno))
            elif name == "add_handler" and len(node.args) >= 2 and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                entries.append((node.args[0].value, node.args[1],
                                node.lineno))
            for hname, value, line in entries:
                if _is_wrapped(value):
                    # Wrapped handlers still owe REPLICATION
                    # visibility: the durable write must flow through
                    # self._journal (the standby tails the journal —
                    # a direct table write is invisible to it and
                    # silently diverges the replica).
                    inner = value.args[0] if (
                        isinstance(value, ast.Call) and value.args) \
                        else None
                    if not (isinstance(inner, ast.Attribute)
                            and isinstance(inner.value, ast.Name)
                            and inner.value.id == "self"):
                        continue
                    target = model.functions.get(
                        f"{prefix}.{inner.attr}")
                    if target is None:
                        continue
                    table = _durable_write_in(model, target)
                    if table and not _journal_call_in(model, target):
                        out.add(info, line, fi.qualname,
                                f"handler {hname!r} writes durable "
                                f"table {table!r} without a "
                                f"self._journal record — the write "
                                f"is invisible to the replication "
                                f"stream (a hot standby diverges) "
                                f"and to restart replay")
                    continue
                if not (isinstance(value, ast.Attribute)
                        and isinstance(value.value, ast.Name)
                        and value.value.id == "self"):
                    continue
                target = model.functions.get(f"{prefix}.{value.attr}")
                if target is None:
                    continue
                table = _durable_write_in(model, target)
                if table:
                    out.add(info, line, fi.qualname,
                            f"handler {hname!r} writes durable table "
                            f"{table!r} but is registered without the "
                            f"_mut/journal wrapper — a head kill -9 "
                            f"loses its acked mutations")
    return out.findings


# --------------------------------------------------------------------------
# rule: lock-order-inversion
# --------------------------------------------------------------------------

def _edge_witness_line(model: ProjectModel, la, a: str, b: str):
    """(ModuleInfo, line, symbol) of the first witness of edge a->b."""
    wits = la.edges.get((a, b))
    if not wits:
        return None
    fn, rel, line, _ve = wits[0]
    fi = model.functions[fn]
    return model.modules[fi.module], line, fn


def _render_edge_chain(la, a: str, b: str) -> str:
    """'mod:Cls.fn acquires 'B' while holding 'A' (held via f -> g)'
    — line-number-free for baseline-stable fingerprints."""
    wits = la.edges.get((a, b), ())
    if not wits:
        return f"{_short_key(a)} -> {_short_key(b)}"
    fn, _rel, _line, via_entry = wits[0]
    msg = (f"{_short_fn(fn)} acquires {_short_key(b)!r} while "
           f"holding {_short_key(a)!r}")
    if via_entry:
        hops = la.chain(fn, a)
        if len(hops) > 1:
            msg += f" (entered holding it via {' -> '.join(hops)})"
    return msg


def rule_lock_order_inversion(model: ProjectModel) -> List[Finding]:
    """Cycles in the global lock-acquisition-order graph: two code
    paths that take the same pair of locks in opposite orders can
    deadlock the moment two threads interleave (the classic ABBA —
    lockdep's central check).  Each finding cites the full cycle with
    one acquisition chain per edge."""
    out = _Collector(model, "lock-order-inversion")
    la = model.lock_analysis()
    for cyc in la.cycles():
        edges = list(zip(cyc, cyc[1:] + cyc[:1]))
        anchor = _edge_witness_line(model, la, *edges[0])
        if anchor is None:
            continue
        info, line, symbol = anchor
        ring = " -> ".join(_short_key(t) for t in cyc + cyc[:1])
        chains = "; ".join(_render_edge_chain(la, a, b)
                           for a, b in edges)
        out.add(info, line, symbol,
                f"lock-order cycle {ring} (potential ABBA "
                f"deadlock): {chains}")
    return out.findings


# --------------------------------------------------------------------------
# rule: wait-holding-foreign-lock
# --------------------------------------------------------------------------

def rule_wait_holding_foreign_lock(model: ProjectModel) -> List[Finding]:
    """``Condition.wait`` releases ONLY the condition's own lock.  Any
    *other* lock held across the wait — taken in this function or
    anywhere up the call chain — stays held for the full wait (and
    with a retry loop, indefinitely): every other thread needing that
    lock stalls behind a sleeper.  Timeouts don't excuse it; they just
    cap each stall."""
    out = _Collector(model, "wait-holding-foreign-lock")
    la = model.lock_analysis()
    for qn in sorted(la.facts):
        fi = model.functions[qn]
        info = model.modules[fi.module]
        entry = la.entry.get(qn, set())
        for w in la.facts[qn].waits:
            if not w.token.is_cond:
                continue  # plain .wait() objects (events, futures)
                #           are blocking-under-lock's jurisdiction
            held_keys = {t.key for t in w.held if t.global_}
            foreign = sorted((held_keys | set(entry))
                             - {w.token.key})
            if not foreign:
                continue
            fdesc = ", ".join(repr(_short_key(k)) for k in foreign)
            how = []
            for k in foreign:
                if k not in held_keys:
                    hops = la.chain(qn, k)
                    if len(hops) > 1:
                        how.append(f"{_short_key(k)!r} held via "
                                   f"{' -> '.join(hops)}")
            suffix = f" ({'; '.join(how)})" if how else ""
            out.add(info, w.line, qn,
                    f"{w.desc}(...) waits on condition "
                    f"{_short_key(w.token.key)!r} while a different "
                    f"lock is held: {fdesc} — wait releases only its "
                    f"own lock{suffix}")
    return out.findings


# --------------------------------------------------------------------------
# rule: rpc-protocol
# --------------------------------------------------------------------------

def rule_rpc_protocol(model: ProjectModel) -> List[Finding]:
    """The string-keyed RPC plane, statically closed: every call names
    a registered handler, every handler has a caller, mutating
    (_mut-registered) handlers are reached only through the
    idempotent/fenced wrappers, and every dispatch loop re-installs
    the request envelope."""
    out = _Collector(model, "rpc-protocol")
    idx = ProtocolIndex.of(model)
    # (a) calls to unregistered handlers — the typo'd method name that
    # otherwise surfaces as a runtime AttributeError on the server.
    if idx.handlers:
        for name in sorted(idx.call_sites):
            if name in idx.handlers:
                continue
            for site in idx.call_sites[name]:
                info = model.modules[site.module]
                out.add(info, site.line, site.symbol,
                        f"rpc call names handler {name!r} which no "
                        f"server table registers")
    # (b) registered handlers nobody calls — dead protocol surface
    # (or externally driven: say so with a reasoned disable).
    for name in sorted(idx.handlers):
        if name in idx.call_sites:
            continue
        for reg in idx.handlers[name]:
            info = model.modules[reg.module]
            out.add(info, reg.line, reg.symbol,
                    f"handler {name!r} is never called from the "
                    f"package (dead protocol surface, or an external "
                    f"caller that deserves a reasoned disable)")
    # (c) mutating handlers invoked through the plain call path:
    # bypasses idempotency dedup AND lease-epoch fencing.
    for name in sorted(idx.handlers):
        regs = idx.handlers[name]
        if not any(r.mutating for r in regs):
            continue
        for site in idx.call_sites.get(name, ()):
            if site.kind in idx.safe_kinds:
                continue
            info = model.modules[site.module]
            out.add(info, site.line, site.symbol,
                    f"mutating handler {name!r} invoked via plain "
                    f"{site.kind!r} — bypasses idempotency dedup and "
                    f"epoch fencing (use mut_call/call_idempotent)")
    # (d) a dispatch loop that decodes envelopes and invokes handlers
    # must re-install the caller's trace + deadline scopes, or every
    # request it serves falls out of the merged timeline and sheds
    # nothing.
    for cls_qn in sorted(model.classes):
        ci = model.classes[cls_qn]
        if not _class_owns_handlers(model, ci):
            continue
        recv_fns = [qn for qn in sorted(ci.methods.values())
                    if _calls_named(model, qn, "_recv_msg")]
        if not recv_fns:
            continue
        installs_trace = installs_deadline = False
        for mqn in ci.methods.values():
            t, d = _scope_installs(model, mqn, depth=2)
            installs_trace |= t
            installs_deadline |= d
        if installs_trace and installs_deadline:
            continue
        missing = []
        if not installs_trace:
            missing.append("tracing.scope_from")
        if not installs_deadline:
            missing.append("deadlines.scope")
        qn = recv_fns[0]
        fi = model.functions[qn]
        info = model.modules[fi.module]
        out.add(info, fi.line, qn,
                f"rpc dispatch path of class {ci.name!r} never "
                f"re-installs the request envelope "
                f"({' + '.join(missing)} missing): handlers run "
                f"without the caller's trace and deadline context")
    return out.findings


def _class_owns_handlers(model: ProjectModel, ci) -> bool:
    for mqn in ci.methods.values():
        for node in model.walk_own(model.functions[mqn].node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "handlers" and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        return True
    return False


def _calls_named(model: ProjectModel, qn: str, name: str) -> bool:
    fi = model.functions.get(qn)
    if fi is None:
        return False
    for node in model.walk_own(fi.node):
        if isinstance(node, ast.Call):
            f = node.func
            cname = f.id if isinstance(f, ast.Name) else \
                getattr(f, "attr", "")
            if cname == name:
                return True
    return False


def _scope_installs(model: ProjectModel, qn: str,
                    depth: int) -> Tuple[bool, bool]:
    """(installs tracing scope, installs deadline scope) within
    ``depth`` confident call hops of ``qn``."""
    trace = dead = False
    fi = model.functions.get(qn)
    if fi is None:
        return False, False
    for node in model.walk_own(fi.node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else \
            (f.id if isinstance(f, ast.Name) else "")
        if attr == "scope_from":
            trace = True
        elif attr == "scope":
            recv = ""
            if isinstance(f, ast.Attribute):
                if isinstance(f.value, ast.Name):
                    recv = f.value.id
                elif isinstance(f.value, ast.Attribute):
                    recv = f.value.attr
            if "deadline" in recv.lower():
                dead = True
    if (trace and dead) or depth <= 0:
        return trace, dead
    for edge in model.call_edges.get(qn, ()):
        if edge.kind == "fallback":
            continue
        t, d = _scope_installs(model, edge.target, depth - 1)
        trace |= t
        dead |= d
        if trace and dead:
            break
    return trace, dead


# --------------------------------------------------------------------------
# rule: exception-contract
# --------------------------------------------------------------------------

# Findings are scoped to the user-facing layers the ISSUE names: a
# typed FT error swallowed into a parent catch there loses the
# recovery dispatch (retry-elsewhere vs re-register vs back-off)
# that some OTHER call site of the same callee implements.
_CONTRACT_SEGMENTS = {"serve", "train", "dag"}


def rule_exception_contract(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "exception-contract")
    idx = ProtocolIndex.of(model)
    for site in idx.try_sites:
        segs = set(site.module.split("."))
        if not (segs & _CONTRACT_SEGMENTS):
            continue
        fi = model.functions[site.symbol]
        info = model.modules[fi.module]
        seen_callees = set()
        for callee, _cline in site.callees:
            if callee in seen_callees:
                continue
            seen_callees.add(callee)
            for t in sorted(idx.callee_raises(callee)):
                # typed clause present -> contract honored
                if any(t in names for _l, names, _b in site.handlers):
                    continue
                peers = [s for s in idx.typed_catches.get(
                    (callee, t), ()) if s is not site]
                if not peers:
                    continue  # nobody handles it typed: no contract
                relevant = [(hl, names, bare)
                            for hl, names, bare in site.handlers
                            if names & FT_TYPED_ERRORS[t]]
                if any(bare for _hl, _n, bare in relevant):
                    continue  # bare re-raise preserves the type
                parent_h = relevant[0][:2] if relevant else None
                peer = peers[0]
                cdesc = callee[4:] + " (rpc)" \
                    if callee.startswith("rpc:") else _short_fn(callee)
                if parent_h is not None:
                    hline, names = parent_h
                    out.add(info, hline, site.symbol,
                            f"call to {cdesc} can raise {t}, but this "
                            f"except catches only the parent "
                            f"({', '.join(sorted(names))}) — "
                            f"{_short_fn(peer.symbol)} handles {t} "
                            f"typed for the same callee")
                else:
                    out.add(info, site.line, site.symbol,
                            f"call to {cdesc} can raise {t}, which "
                            f"escapes every except clause here — "
                            f"{_short_fn(peer.symbol)} handles {t} "
                            f"typed for the same callee")
    return out.findings


# --------------------------------------------------------------------------
# rule: crash-handler-safety
# --------------------------------------------------------------------------

_CRASH_DEPTH = 4
_CRASH_METRIC_MODULES = ("observability.metrics", "observability.tsdb")
_CRASH_RPC_ATTRS = _RPC_BLOCKING_ATTRS | {"call_async", "mut_call",
                                          "publish"}
# confident edge kinds only: one class-blind unique-name guess must not
# smear "reachable from a crash hook" across the package
_CRASH_EDGE_KINDS = ("self", "local", "module", "import", "init")


def _crash_ref(model: ProjectModel, info: ModuleInfo, fi: FuncInfo,
               expr: ast.AST) -> Optional[str]:
    """Resolve a BARE function reference (hook installation passes the
    function, it doesn't call it): ``self._hook`` / ``local_fn``."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id == "self" and fi.cls is not None:
        return model._method_on(info.name, fi.cls, expr.attr)
    if isinstance(expr, ast.Name):
        return model._resolve_name(info, fi, expr.id)
    return None


def _crash_roots(model: ProjectModel) -> Dict[str, str]:
    """qualname -> how-installed for every function registered as a
    crash hook: ``sys.excepthook``/``threading.excepthook`` assignment
    targets, ``signal.signal(...)`` handlers, and ``atexit.register``
    callbacks — the latter only in modules that also call
    ``faulthandler.enable`` (ordinary shutdown hooks are NOT crash
    code; a module wiring faulthandler is doing crash forensics and
    its atexit hook runs on fatal paths it must not deadlock)."""
    roots: Dict[str, str] = {}
    fh_modules = set()
    for fi in model.functions.values():
        info = model.modules[fi.module]
        for node in model.walk_own(fi.node):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "enable" and \
                    isinstance(node.func.value, ast.Name) and \
                    info.imports.get(node.func.value.id,
                                     node.func.value.id) == "faulthandler":
                fh_modules.add(fi.module)
    for fi in list(model.functions.values()):
        info = model.modules[fi.module]
        for node in model.walk_own(fi.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "excepthook" and \
                            isinstance(t.value, ast.Name) and \
                            info.imports.get(t.value.id, t.value.id) in (
                                "sys", "threading"):
                        qn = _crash_ref(model, info, fi, node.value)
                        if qn is not None:
                            roots.setdefault(
                                qn, f"{t.value.id}.excepthook")
            elif isinstance(node, ast.Call):
                f = node.func
                if not isinstance(f, ast.Attribute) or \
                        not isinstance(f.value, ast.Name):
                    continue
                base = info.imports.get(f.value.id, f.value.id)
                if f.attr == "signal" and base == "signal" and \
                        len(node.args) >= 2:
                    qn = _crash_ref(model, info, fi, node.args[1])
                    if qn is not None:
                        roots.setdefault(qn, "signal handler")
                elif f.attr == "register" and base == "atexit" and \
                        node.args and fi.module in fh_modules:
                    qn = _crash_ref(model, info, fi, node.args[0])
                    if qn is not None:
                        roots.setdefault(qn, "atexit hook in a "
                                             "faulthandler module")
    return roots


def _crash_violations(model: ProjectModel, info: ModuleInfo,
                      fi: FuncInfo) -> List[Tuple[int, str]]:
    """(line, description) for every op a crash hook must not perform:
    lock acquisition, metrics/TSDB-plane calls, RPC/pubsub."""
    out: List[Tuple[int, str]] = []
    for node in model.walk_own(fi.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lock = model.lock_context(info, fi, item.context_expr)
                if lock is not None:
                    out.append((node.lineno,
                                f"takes lock {lock[0]!r}"))
            continue
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        if f.attr == "acquire":
            lock = model.lock_context(info, fi, f.value)
            if lock is not None:
                out.append((node.lineno,
                            f"acquires lock {lock[0]!r}"))
            continue
        if f.attr in _CRASH_RPC_ATTRS:
            out.append((node.lineno,
                        f"performs RPC {call_desc(node)}(...)"))
            continue
        if isinstance(f.value, ast.Name):
            target = info.imports.get(f.value.id, "")
            if target.endswith(_CRASH_METRIC_MODULES):
                out.append((node.lineno,
                            f"allocates via the metrics plane "
                            f"({call_desc(node)})"))
    return out


def rule_crash_handler_safety(model: ProjectModel) -> List[Finding]:
    out = _Collector(model, "crash-handler-safety")
    viol_memo: Dict[str, List[Tuple[int, str]]] = {}
    for root_qn, how in sorted(_crash_roots(model).items()):
        seen = {root_qn}
        queue: List[Tuple[str, List[str]]] = [(root_qn, [])]
        while queue:
            qn, path = queue.pop(0)
            fi = model.functions.get(qn)
            if fi is None:
                continue
            info = model.modules[fi.module]
            if qn not in viol_memo:
                viol_memo[qn] = _crash_violations(model, info, fi)
            for line, desc in viol_memo[qn]:
                via = (f" via {' -> '.join(path)}" if path else "")
                out.add(info, line, fi.qualname,
                        f"{desc} on a path reachable from crash hook "
                        f"{_short_fn(root_qn)} ({how}){via} — crash "
                        f"hooks are flush-to-fd only")
            if len(path) >= _CRASH_DEPTH:
                continue
            for e in model.call_edges.get(qn, ()):
                if e.kind not in _CRASH_EDGE_KINDS or e.target in seen:
                    continue
                callee = model.functions.get(e.target)
                if callee is not None and callee.module.endswith(
                        _CRASH_METRIC_MODULES):
                    via = (f" via {' -> '.join(path)}" if path else "")
                    out.add(info, e.line, fi.qualname,
                            f"allocates via the metrics plane "
                            f"({e.via}) on a path reachable from "
                            f"crash hook {_short_fn(root_qn)} "
                            f"({how}){via} — crash hooks are "
                            f"flush-to-fd only")
                    continue
                seen.add(e.target)
                queue.append((e.target, path + [f"{e.via}()"]))
    return out.findings


# --------------------------------------------------------------------------
# rule: host-device-sync
# --------------------------------------------------------------------------

def rule_host_device_sync(model: ProjectModel) -> List[Finding]:
    """Implicit blocking device->host transfers on traced values in
    hot-path methods: ``float()``/``int()``/``bool()``/``.item()``/
    ``np.asarray()``/truth-testing/``print`` applied to a value the
    dataflow lattice proves may hold a ``jax.Array``.  Each one stalls
    the dispatch queue for a full device round-trip per call.
    ``jax.device_get``/``block_until_ready`` are explicit boundaries
    (exempt), and so is anything under a ``*.annotation(...)`` block —
    the device plane's declared-sync idiom."""
    out = _Collector(model, "host-device-sync")
    flow = model.device_flow()
    for qn in sorted(model.functions):
        fi = model.functions[qn]
        if qn in flow.jitted:
            continue               # runs under trace — cannot sync
        if not hot_paths.sync_hot(fi.name):
            continue
        ff = flow.flows.get(qn)
        if ff is None:
            continue
        info = model.modules[fi.module]
        seen: Set[Tuple[int, str, str]] = set()
        for site in ff.sync_sites:
            if site.annotated:
                continue
            key = (site.line, site.kind, site.expr)
            if key in seen:
                continue
            seen.add(key)
            out.add(info, site.line, fi.qualname,
                    f"{site.kind} on traced value `{site.expr}` in "
                    f"hot-path method {fi.name!r} forces a blocking "
                    f"device->host transfer per call — defer it off "
                    f"the hot path, make the boundary explicit with "
                    f"jax.device_get, or declare it with a "
                    f"device.annotation(...) block")
    return out.findings


# --------------------------------------------------------------------------
# rule: recompile-hazard
# --------------------------------------------------------------------------

def rule_recompile_hazard(model: ProjectModel) -> List[Finding]:
    """Two static recompile-storm shapes, cross-referenced with the
    runtime ``ray_tpu_xla_compiles`` series the device plane already
    tracks: (a) a jitted wrapper fed per-call-varying Python scalars
    (``len(x)``, ``int(x)``, ``x.shape[i]``) without
    ``static_argnums``/``static_argnames`` — every distinct value is a
    fresh trace+compile; (b) Python ``if``/``while`` on ``.shape``/
    ``len()`` inside a jitted body — legal (shapes are static under
    trace) but every distinct shape class re-traces, so unbucketed
    inputs compile without bound."""
    out = _Collector(model, "recompile-hazard")
    flow = model.device_flow()
    for qn in sorted(model.functions):
        fi = model.functions[qn]
        info = model.modules[fi.module]
        ff = flow.flows.get(qn)
        if ff is not None and not hot_paths.is_builder(fi.name):
            seen: Set[Tuple[int, str]] = set()
            for wc in ff.wrapper_calls:
                if wc.build.has_static:
                    continue       # bucketing/static args declared
                descs = [a.scalar_desc for a in wc.args
                         if a.scalar_desc is not None]
                descs += [f"{k}={d}" for k, d in wc.kw_scalars]
                for desc in descs:
                    key = (wc.line, desc)
                    if key in seen:
                        continue
                    seen.add(key)
                    out.add(info, wc.line, fi.qualname,
                            f"jitted wrapper is fed per-call-varying "
                            f"Python scalar `{desc}` but its "
                            f"{wc.build.desc} build declares no "
                            f"static_argnums/static_argnames — every "
                            f"distinct value re-traces and recompiles "
                            f"(watch the ray_tpu_xla_compiles series "
                            f"climb); declare it static or bucket it")
        for sb in flow.shape_branches.get(qn, ()):
            out.add(info, sb.line, fi.qualname,
                    f"shape-dependent Python branch `{sb.desc}` "
                    f"inside a jitted body — each distinct input "
                    f"shape traces a fresh program (the "
                    f"ray_tpu_xla_compiles recompile-storm class); "
                    f"bucket input shapes or branch on traced values "
                    f"with jnp.where/lax.cond")
    return out.findings


# --------------------------------------------------------------------------
# rule: missing-donation
# --------------------------------------------------------------------------

def rule_missing_donation(model: ProjectModel) -> List[Finding]:
    """A jitted state-update call whose input buffer is provably dead
    after the call — overwritten by the call's own result (the
    ``params, opt = update(params, opt, ...)`` shape), a fresh inline
    device temporary, or a single-use local — while the wrapper build
    lacks ``donate_argnums`` for that position.  Donation lets XLA
    alias the output into the input buffer; without it both copies
    stay live across the call, the 2x HBM headroom class
    ``train/optim.py`` already exploits."""
    out = _Collector(model, "missing-donation")
    flow = model.device_flow()
    for qn in sorted(model.functions):
        ff = flow.flows.get(qn)
        if ff is None:
            continue
        fi = model.functions[qn]
        info = model.modules[fi.module]
        seen: Set[Tuple[int, int]] = set()
        for wc in ff.wrapper_calls:
            b = wc.build
            if b.donate_names:
                continue           # name-based donation: can't map
            wname = b.key or b.desc
            for a in wc.args:
                if wc.starred_from is not None and \
                        a.index >= wc.starred_from:
                    continue       # indices past *args are unknown
                if a.index in b.donated:
                    continue
                if a.key is not None and a.key in wc.target_keys:
                    why = (f"argument {a.index} (`{a.key}`) is "
                           f"overwritten by the call's own result")
                elif a.fresh_device_temp and not b.donated:
                    # A build that already donates its state arg has
                    # made the donation decision; staging temps next
                    # to a donated KV cache are not the 2x class.
                    why = (f"argument {a.index} is a fresh device "
                           f"temporary no other reference can see")
                elif a.dead_local:
                    why = (f"argument {a.index} (`{a.key}`) is a "
                           f"single-use local, dead after the call")
                else:
                    continue
                key = (wc.line, a.index)
                if key in seen:
                    continue
                seen.add(key)
                out.add(info, wc.line, fi.qualname,
                        f"{why}, but the {b.desc} build of "
                        f"`{wname}` does not donate it — add "
                        f"donate_argnums={a.index} so XLA reuses the "
                        f"input buffer in place (2x HBM headroom on "
                        f"the updated state, as train/optim.py does)")
    return out.findings


# --------------------------------------------------------------------------
# rule: sharding-contract
# --------------------------------------------------------------------------

_SPEC_KWARGS = {"in_specs", "out_specs", "in_shardings",
                "out_shardings"}


def rule_sharding_contract(model: ProjectModel) -> List[Finding]:
    """Literal axis names in pjit/``shard_map`` partition specs (and
    ``NamedSharding`` descriptors) must name axes some mesh
    constructible in this package actually carries — the vocabulary
    harvested from ``Mesh(...)`` axis tuples, ``*AXIS*`` constants,
    and the MeshSpec/ShardingRules fields in parallel/sharding.py.  A
    drifted axis string fails only at trace time on a real mesh;
    non-literal specs (built through rule tables) are trusted."""
    out = _Collector(model, "sharding-contract")
    flow = model.device_flow()
    axes = flow.mesh_axes
    if not axes:
        return out.findings        # no mesh builders: nothing to check
    known = ", ".join(sorted(axes))
    for qn in sorted(model.functions):
        fi = model.functions[qn]
        info = model.modules[fi.module]
        for node in model.walk_own(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fname = (node.func.attr
                     if isinstance(node.func, ast.Attribute)
                     else getattr(node.func, "id", ""))
            spec_exprs: List[ast.AST] = []
            if fname in ("shard_map", "pjit"):
                for kw in node.keywords:
                    if kw.arg in _SPEC_KWARGS:
                        spec_exprs.append(kw.value)
            elif fname == "NamedSharding" and len(node.args) >= 2:
                spec_exprs.append(node.args[1])
            for spec in spec_exprs:
                for sub in ast.walk(spec):
                    if not (isinstance(sub, ast.Call) and
                            (getattr(sub.func, "id", "") in
                             ("P", "PartitionSpec")
                             or getattr(sub.func, "attr", "") ==
                             "PartitionSpec")):
                        continue
                    for bad in _bad_literal_axes(sub, axes):
                        out.add(
                            info, sub.lineno, fi.qualname,
                            f"partition spec names axis "
                            f"'{bad}' but no mesh "
                            f"constructible in this package "
                            f"carries it (known axes: {known}) "
                            f"— the spec fails at trace time on "
                            f"a real mesh")
    return out.findings


def _bad_literal_axes(spec_call: ast.Call,
                      axes: Set[str]) -> List[str]:
    """Axis strings appearing DIRECTLY in a P(...)/PartitionSpec(...)
    call (bare literals or literal tuples — computed expressions like
    ``P(*d['spec'])`` are trusted) that no known mesh carries."""
    out: List[str] = []
    for arg in spec_call.args:
        elts = (arg.elts if isinstance(arg, (ast.Tuple, ast.List))
                else [arg])
        for e in elts:
            if isinstance(e, ast.Constant) and \
                    isinstance(e.value, str) and e.value not in axes:
                out.append(e.value)
    return out


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

RULES = {
    "blocking-under-lock": rule_blocking_under_lock,
    "handler-idempotency": rule_handler_idempotency,
    "trace-propagation": rule_trace_propagation,
    "ft-exception-swallow": rule_ft_exception_swallow,
    "resource-teardown": rule_resource_teardown,
    "thread-hygiene": rule_thread_hygiene,
    "unbounded-mailbox": rule_unbounded_mailbox,
    "log-hygiene": rule_log_hygiene,
    "metric-cardinality": rule_metric_cardinality,
    "jit-in-hot-path": rule_jit_in_hot_path,
    "suppression-syntax": rule_suppression_syntax,
    "journaled-mutation": rule_journaled_mutation,
    "lock-order-inversion": rule_lock_order_inversion,
    "wait-holding-foreign-lock": rule_wait_holding_foreign_lock,
    "rpc-protocol": rule_rpc_protocol,
    "exception-contract": rule_exception_contract,
    "crash-handler-safety": rule_crash_handler_safety,
    "host-device-sync": rule_host_device_sync,
    "recompile-hazard": rule_recompile_hazard,
    "missing-donation": rule_missing_donation,
    "sharding-contract": rule_sharding_contract,
}

RULE_DOCS = {
    "blocking-under-lock": (
        "Blocking operations (RPC call/retry, socket recv/accept, "
        "time.sleep, un-timeouted wait/get/acquire/join/result) "
        "executed — directly or through the call graph — while a "
        "threading.Lock/RLock is held.  The framework's deadlock "
        "class: one stalled RPC under a hot lock wedges every other "
        "holder."),
    "handler-idempotency": (
        "Mutating handlers (register_*/remove_*/create_*/drain_*/"
        "kill_*/*_put/*_del) in an RpcServer table must be wrapped in "
        "_mut/idempotent_handler so client retries after a lost "
        "response replay the first reply instead of re-applying."),
    "trace-propagation": (
        "Task bundles must carry the 'trace' field, accepted trace "
        "parameters must be propagated (tracing.scope_from), and "
        "driver-side root ops (dag execute, serve handle.remote, "
        "train_step) must mint a span — otherwise the merged cluster "
        "timeline loses the hop."),
    "ft-exception-swallow": (
        "A broad except around FT-capable calls (RPC results re-raise "
        "server-shipped errors; channel reads raise typed FT errors) "
        "that neither re-raises, uses, nor logs the error silently "
        "eats ActorError/ChannelError/ObjectLostError — the recovery "
        "paths keyed on those types never fire."),
    "resource-teardown": (
        "Channels, sockets, RPC servers/clients and open files must "
        "be closed on some path: self-stored resources need a "
        "teardown method that closes them; locals must be closed, "
        "returned, stored, or passed onward."),
    "thread-hygiene": (
        "threading.Thread needs daemon= (non-daemon leaks block "
        "interpreter exit), and a thread stored on self is long-lived "
        "infrastructure: some teardown path must join it."),
    "unbounded-mailbox": (
        "A self-stored queue.Queue()/deque()/list mailbox appended on "
        "an RPC/dispatch path (submit/handle/push/recv/...) with no "
        "bound check in the method is the OOM-under-overload failure "
        "class: demand-driven queues must reject (BackPressureError / "
        "maxsize) or carry a reasoned disable."),
    "log-hygiene": (
        "Logger calls on dispatch/hot-path methods must pass lazy "
        "%-style args (no f-string/.format/%/concat pre-formatting — "
        "the cost is paid even when the level is off), and runtime "
        "modules must not use bare print() (unleveled, untraced, "
        "unshipped output; CLI entry points are exempt)."),
    "metric-cardinality": (
        "Metric tag values must be bounded: a tag fed an unbounded "
        "identifier (object/trace/task/request id, uuid, .hex() "
        "rendering) mints one series per operation, growing every "
        "process registry, the /metrics exposition, and the head "
        "TSDB until the cardinality cap drops real series."),
    "jit-in-hot-path": (
        "jax.jit/pjit invoked inside dispatch/step/per-request "
        "methods builds a fresh wrapper (with its own compile cache) "
        "per call — every invocation re-traces and recompiles, the "
        "recompilation-storm failure class the device plane's "
        "xla-recompile-storm alert fires on.  Build the jitted "
        "program once (builder/init) or cache it behind a None "
        "guard."),
    "suppression-syntax": (
        "raylint disables must name real rules and carry a "
        "'-- reason'; a reasonless or typo'd disable does not "
        "suppress anything."),
    "journaled-mutation": (
        "Any RPC handler that writes a durable head table (_kv, "
        "_actors, _named, _pgs — directly or through self-method "
        "calls) must be registered through the _mut/journal wrapper: "
        "it journals + fsyncs the redo records before the reply "
        "ships.  An unwrapped writer acks mutations a head kill -9 "
        "silently loses, and skips idempotency dedup and epoch "
        "fencing besides.  Wrapped handlers are additionally checked "
        "for REPLICATION VISIBILITY: the durable write must emit a "
        "self._journal redo record (or ride the _apply_record replay "
        "path) — the hot standby tails the journal, so a direct "
        "table write never ships and the replica silently diverges."),
    "lock-order-inversion": (
        "Cycles in the global lock-acquisition-order graph (built "
        "from the interprocedural lock-set analysis: which locks may "
        "be held when each function runs, propagated over the call "
        "graph).  Two paths taking the same locks in opposite orders "
        "deadlock the moment two threads interleave — lockdep's ABBA "
        "check, at lint time.  Each finding cites the full cycle "
        "with one acquisition chain per edge."),
    "wait-holding-foreign-lock": (
        "Condition.wait releases ONLY the condition's own lock; any "
        "other lock held across the wait — locally or anywhere up "
        "the call chain — stays held for the full wait, stalling "
        "every other thread that needs it.  Timeouts cap each stall, "
        "they don't excuse it."),
    "rpc-protocol": (
        "The string-keyed RPC plane statically closed: every "
        ".call/mut_call/call_idempotent site must name a registered "
        "handler, every registered handler needs a caller (dead "
        "protocol otherwise), _mut-registered mutating handlers must "
        "be reached via mut_call/call_idempotent (plain call skips "
        "idempotency dedup and epoch fencing), and a handler "
        "dispatch loop must re-install the envelope's trace + "
        "deadline scopes."),
    "exception-contract": (
        "Typed-FT-error contracts at the user-facing layers (serve/"
        "train/dag): if a callee can raise a typed error "
        "(StaleEpochError, DeadlineExceededError, ChannelError, "
        "ActorDiedError, BackPressureError — inferred over the call "
        "graph AND through the RPC boundary) and some other call "
        "site handles it typed, a try here that catches only a "
        "parent class (or lets it escape its clauses) silently "
        "drops the recovery dispatch the typed handler implements."),
    "crash-handler-safety": (
        "Code reachable from crash hooks (sys.excepthook/"
        "threading.excepthook assignments, signal handlers, atexit "
        "callbacks registered by faulthandler-wiring modules) must "
        "not take locks, allocate via the metrics/TSDB plane, or "
        "perform RPC: the hook may run with arbitrary locks already "
        "held by the dying thread, so anything beyond flush-to-fd "
        "(os.write to a pre-opened fd) can deadlock the process "
        "during its last breath and lose the flight record."),
    "host-device-sync": (
        "Implicit blocking device->host transfers on traced values "
        "(returns of jitted callables, params/caches, collective "
        "outputs — tracked by the device-plane dataflow lattice) in "
        "hot-path methods: float()/int()/bool()/.item()/np.asarray/"
        "truth-testing/print each stall the dispatch queue for a "
        "device round-trip per call.  jax.device_get and "
        "block_until_ready are explicit boundaries; sites under a "
        "device.annotation(...) block are declared syncs."),
    "recompile-hazard": (
        "Static recompile-storm shapes, the compile-time half of the "
        "runtime ray_tpu_xla_compiles tracking: jitted wrappers fed "
        "per-call-varying Python scalars (len/int/.shape[i]) without "
        "static_argnums/static_argnames, and shape-dependent Python "
        "branches inside jitted bodies — every distinct value or "
        "shape class traces and compiles a fresh program."),
    "missing-donation": (
        "A jitted state-update call whose input buffer is provably "
        "dead after the call (overwritten by the call's own result, "
        "a fresh inline device temporary, or a single-use local) "
        "while the jit build lacks donate_argnums for that position "
        "— without donation both buffers stay live across the call, "
        "halving HBM headroom on the updated state."),
    "sharding-contract": (
        "Literal axis names in pjit/shard_map partition specs and "
        "NamedSharding descriptors must belong to the axis "
        "vocabulary of meshes constructible in this package (Mesh "
        "axis tuples, AXIS_ORDER constants, MeshSpec/ShardingRules "
        "fields) — a drifted axis string only fails at trace time "
        "on a real mesh."),
}
