"""raylint — framework-aware static analysis for ray_tpu.

A single AST parse of the whole package feeds rules that enforce the
distributed-runtime invariants the test suite can only sample:

- ``blocking-under-lock``   deadlock class: RPC/sleep/unbounded waits
                            reachable while a threading lock is held
- ``handler-idempotency``   mutating RpcServer handlers must ride
                            ``_mut``/``idempotent_handler``
- ``trace-propagation``     bundles carry 'trace', trace params are
                            used, root ops mint spans
- ``ft-exception-swallow``  broad excepts must not eat typed FT errors
- ``resource-teardown``     channels/sockets/servers need a reachable
                            close on some path
- ``thread-hygiene``        daemon= required; self-stored threads need
                            a teardown join
- ``unbounded-mailbox``     demand-driven queues must bound or reject
- ``log-hygiene``           lazy %-args on hot-path logger calls; no
                            bare print() in runtime modules
- ``suppression-syntax``    disables must name real rules + a reason
- ``journaled-mutation``    durable-table handlers ride the journal/
                            _mut wrapper
- ``lock-order-inversion``  ABBA cycles in the global lock-order
                            graph (interprocedural lock-set model)
- ``wait-holding-foreign-lock``  Condition.wait with a different
                            lock held (locally or via callers)
- ``rpc-protocol``          string-keyed RPC plane closed: no
                            unregistered/dead handlers, mutations
                            ride the fenced path, dispatch loops
                            re-install the envelope
- ``exception-contract``    typed FT errors caught typed where a
                            typed handler exists for the callee
- ``jit-in-hot-path``       jit/pjit wrappers built per call inside
                            dispatch/decode/step loops
- ``host-device-sync``      implicit blocking device->host transfers
                            (float()/.item()/np.asarray/truth-tests/
                            print) on traced values in hot paths
- ``recompile-hazard``      per-call-varying Python scalars into
                            non-static jitted wrappers; shape
                            branching inside jitted bodies
- ``missing-donation``      jitted state updates whose input buffer
                            is dead after the call but not donated
- ``sharding-contract``     literal partition-spec axes must name
                            axes some constructible mesh carries

The device-plane rules ride a conservative traced-value lattice
(``model.DeviceFlow``): values provably holding ``jax.Array``\\ s —
returns of jitted callables, device-module results, collective
outputs — are propagated intraprocedurally and across confident
call-graph edges, and a shared hot-path classifier
(``model.hot_paths``) decides which methods sit on dispatch/decode/
train loops.

Suppress a finding in place::

    something_flagged()  # raylint: disable=<rule> -- why it is safe

grandfather pre-existing debt in ``tools/raylint_baseline.json``
(regenerate with ``ray_tpu lint --update-baseline``), or apply the
mechanically-safe autofixes with ``ray_tpu lint --fix`` (preview with
``--fix --diff``).

Programmatic entry point: :func:`run_lint`.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from . import baseline as baseline_mod
from .model import ProjectModel
from .rules import RULE_DOCS, RULES, Finding

__all__ = ["run_lint", "default_package_root", "default_baseline_path",
           "ProjectModel", "Finding", "RULES", "RULE_DOCS"]


def default_package_root() -> str:
    """The installed ray_tpu package directory (what 'ray_tpu lint'
    analyzes when no path is given)."""
    import ray_tpu

    return os.path.dirname(os.path.abspath(ray_tpu.__file__))


def default_baseline_path(root: Optional[str] = None) -> str:
    """``tools/raylint_baseline.json`` next to the package dir (the
    repo layout); callers pass --baseline for anything else."""
    root = root or default_package_root()
    return os.path.join(os.path.dirname(root), "tools",
                        "raylint_baseline.json")


def run_lint(root: Optional[str] = None, *,
             select: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             use_baseline: bool = True) -> List[Finding]:
    """Parse ``root`` once, run the selected rules, apply the
    baseline.  Returns ALL findings — gate on
    ``[f for f in findings if not f.baselined]``."""
    root = root or default_package_root()
    model = ProjectModel(root)
    rule_names = list(select) if select else list(RULES)
    unknown = [r for r in rule_names if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
    findings: List[Finding] = []
    for name in rule_names:
        findings.extend(RULES[name](model))
    for relpath, err in model.parse_errors:
        findings.append(Finding(
            rule="parse-error", path=relpath, line=1,
            symbol="<module>", message=f"file failed to parse: {err}"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if use_baseline:
        path = baseline_path or default_baseline_path(root)
        baseline_mod.apply(findings, baseline_mod.load(path))
    return findings
