"""RPC-protocol and exception-contract indexes.

The control plane is STRING-KEYED: ``RpcServer({"name": fn, ...})``
tables on the servers, ``client.call("name", ...)`` (and the
``call_retry`` / ``call_idempotent`` / ``mut_call`` wrappers) on the
callers.  Nothing ties the two ends together at runtime until a call
fails with ``no rpc method`` — and nothing at all notices a handler
nobody calls, or a mutating handler invoked through the plain
non-idempotent path.  This module builds the whole-program index both
ends share:

- every registered handler (name, wrapper, resolved target function,
  registration site), across every server table in the package;
- every string-literal call site (name, calling wrapper, site);
- per-function TYPED-FT-RAISE sets: which of the typed fault-tolerance
  errors (``StaleEpochError``, ``DeadlineExceededError``,
  ``ChannelError``, ``ActorDiedError``, ``BackPressureError``) a
  function can raise — directly, through confident call-graph edges,
  and THROUGH the RPC boundary (a ``.call("m")`` site can raise
  whatever the handler for ``m`` raises, since server errors re-raise
  at ``result()``); calls inside a ``try`` that catches a type do not
  propagate it.

The ``rpc-protocol`` and ``exception-contract`` rules in rules.py are
thin reporters over this index.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .model import FuncInfo, ModuleInfo, ProjectModel

# Client-side attribute methods that take the rpc method name as their
# first positional argument.
CALL_ATTRS = {"call", "call_async", "call_with_retry", "call_retry",
              "call_idempotent", "mut_call"}
# Wrappers that give a call idempotency (and, for mut_call, epoch
# fencing): safe paths for a mutating handler.
MUTATION_SAFE_KINDS = {"call_idempotent", "mut_call"}
# Registration-side wrappers that mark a handler MUTATING (journaled /
# idempotency-deduped): calls to it must ride a MUTATION_SAFE kind.
MUTATING_WRAPPERS = {"_mut", "idempotent_handler"}
# Value-transport wrappers that do not change call semantics.
TRANSPARENT_WRAPPERS = {"_sealed"}

# The typed FT errors of exceptions.py, with every PARENT class a
# catch clause could use instead (catching the parent loses the typed
# dispatch the recovery paths key on).
FT_TYPED_ERRORS: Dict[str, FrozenSet[str]] = {
    "ActorDiedError": frozenset({"ActorError", "RayTpuError",
                                 "Exception", "BaseException"}),
    "BackPressureError": frozenset({"RayTpuError", "Exception",
                                    "BaseException"}),
    "ChannelError": frozenset({"RayTpuError", "Exception",
                               "BaseException"}),
    "DeadlineExceededError": frozenset({"RayTpuError", "TimeoutError",
                                        "Exception", "BaseException"}),
    "StaleEpochError": frozenset({"RayTpuError", "Exception",
                                  "BaseException"}),
}

_RAISE_DEPTH_KINDS = ("self", "local", "module", "import", "init")


@dataclass
class HandlerReg:
    name: str
    wrapper: str                  # "" | "_mut" | "idempotent_handler" | ...
    target: Optional[str]         # resolved handler function qualname
    module: str
    line: int
    symbol: str                   # enclosing function qualname

    @property
    def mutating(self) -> bool:
        return self.wrapper in MUTATING_WRAPPERS


@dataclass
class CallSite:
    name: str
    kind: str                     # one of CALL_ATTRS or "retry_call"
    module: str
    line: int
    symbol: str


@dataclass
class TrySite:
    """One try-statement wrapping RPC/FT-capable calls: which callees
    its body reaches and what its except clauses catch."""
    module: str
    line: int                     # the try's line
    symbol: str
    callees: List[Tuple[str, int]] = field(default_factory=list)
    # per handler: (line, caught names, body is a bare re-raise)
    handlers: List[Tuple[int, FrozenSet[str], bool]] = \
        field(default_factory=list)


class ProtocolIndex:
    """Built once per lint run (``ProtocolIndex.of(model)``)."""

    def __init__(self, model: ProjectModel):
        self.model = model
        self.handlers: Dict[str, List[HandlerReg]] = {}
        self.call_sites: Dict[str, List[CallSite]] = {}
        # function qualname -> typed FT errors it may raise
        self.raises: Dict[str, FrozenSet[str]] = {}
        # (callee key, typed error) -> try-sites that catch it TYPED;
        # callee key is a function qualname or "rpc:<method>"
        self.typed_catches: Dict[Tuple[str, str], List[TrySite]] = {}
        self.try_sites: List[TrySite] = []
        self._scan_registrations()
        self._scan_call_sites()
        self._infer_raises()
        self._scan_tries()

    @classmethod
    def of(cls, model: ProjectModel) -> "ProtocolIndex":
        idx = getattr(model, "_protocol_index", None)
        if idx is None:
            idx = cls(model)
            model._protocol_index = idx
        return idx

    # -------------------------------------------------- registrations
    def _scan_registrations(self) -> None:
        for qn in sorted(self.model.functions):
            fi = self.model.functions[qn]
            info = self.model.modules[fi.module]
            for node in self.model.walk_own(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else "")
                if name == "RpcServer" and node.args and \
                        isinstance(node.args[0], ast.Dict):
                    table = node.args[0]
                    for key, value in zip(table.keys, table.values):
                        if isinstance(key, ast.Constant) and \
                                isinstance(key.value, str):
                            self._add_handler(info, fi, key.value,
                                              value, key.lineno)
                elif name == "add_handler" and len(node.args) >= 2 and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    self._add_handler(info, fi, node.args[0].value,
                                      node.args[1], node.lineno)

    def _add_handler(self, info: ModuleInfo, fi: FuncInfo, name: str,
                     value: ast.AST, line: int) -> None:
        wrapper = ""
        inner = value
        hops = 0
        while isinstance(inner, ast.Call) and hops < 3:
            wf = inner.func
            wname = wf.id if isinstance(wf, ast.Name) else (
                wf.attr if isinstance(wf, ast.Attribute) else "")
            if wname in MUTATING_WRAPPERS:
                wrapper = wname
            elif wname in TRANSPARENT_WRAPPERS:
                pass
            else:
                break
            inner = inner.args[0] if inner.args else None
            hops += 1
        target = None
        if isinstance(inner, ast.Attribute) and \
                isinstance(inner.value, ast.Name) and \
                inner.value.id == "self" and fi.cls is not None:
            target = self.model._method_on(fi.module, fi.cls,
                                           inner.attr)
        elif isinstance(inner, ast.Name):
            target = self.model._resolve_name(info, fi, inner.id)
        self.handlers.setdefault(name, []).append(HandlerReg(
            name=name, wrapper=wrapper, target=target,
            module=fi.module, line=line, symbol=fi.qualname))

    # ----------------------------------------------------- call sites
    def _scan_call_sites(self) -> None:
        self._find_forwarders()
        for qn in sorted(self.model.functions):
            fi = self.model.functions[qn]
            for node in self.model.walk_own(fi.node):
                site = self._call_site_of(fi, node)
                if site is not None:
                    self.call_sites.setdefault(site.name,
                                               []).append(site)

    def _find_forwarders(self) -> None:
        """Methods that forward their own parameter as the rpc method
        name (``def _call(self, method, ...): ...
        self._rpc.call(method, ...)``): call sites of such a
        trampoline with a literal first argument are RPC call sites
        too — the thin-client/`mut_call` shape.  A forwarder is
        mutation-safe only if EVERY inner path it forwards to is."""
        self.forwarders: Dict[str, Set[str]] = {}
        for qn in sorted(self.model.functions):
            fi = self.model.functions[qn]
            fnode = fi.node
            params = [a.arg for a in (list(fnode.args.posonlyargs)
                                      + list(fnode.args.args))
                      if a.arg != "self"]
            if not params:
                continue
            first = params[0]
            for node in self.model.walk_own(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                inner = ""
                if isinstance(f, ast.Attribute) and \
                        f.attr in CALL_ATTRS and node.args and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id == first:
                    inner = f.attr
                elif isinstance(f, ast.Name) and \
                        f.id == "retry_call" and \
                        len(node.args) >= 2 and \
                        isinstance(node.args[1], ast.Name) and \
                        node.args[1].id == first:
                    inner = "retry_call"
                if inner:
                    self.forwarders.setdefault(fi.name,
                                               set()).add(inner)
        self.safe_kinds: Set[str] = set(MUTATION_SAFE_KINDS)
        for name, inners in self.forwarders.items():
            if name in CALL_ATTRS:
                continue  # the primitives keep their own semantics
            if inners <= MUTATION_SAFE_KINDS:
                self.safe_kinds.add(name)

    def _call_site_of(self, fi: FuncInfo,
                      node: ast.AST) -> Optional[CallSite]:
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        attrs = CALL_ATTRS | set(getattr(self, "forwarders", ()))
        if isinstance(f, ast.Attribute) and f.attr in attrs and \
                node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return CallSite(node.args[0].value, f.attr, fi.module,
                            node.lineno, fi.qualname)
        if isinstance(f, ast.Name):
            if f.id == "retry_call" and len(node.args) >= 2 and \
                    isinstance(node.args[1], ast.Constant) and \
                    isinstance(node.args[1].value, str):
                return CallSite(node.args[1].value, "retry_call",
                                fi.module, node.lineno, fi.qualname)
            if f.id in getattr(self, "forwarders", ()) and \
                    node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                return CallSite(node.args[0].value, f.id, fi.module,
                                node.lineno, fi.qualname)
        return None

    def rpc_raises(self, method: str) -> FrozenSet[str]:
        """Typed errors a call to rpc ``method`` can re-raise at the
        caller: the handler target's raise set, plus StaleEpochError
        for _mut-registered handlers (the fence rejects superseded
        epochs before the handler runs)."""
        out: Set[str] = set()
        for reg in self.handlers.get(method, ()):
            if reg.target:
                out |= self.raises.get(reg.target, frozenset())
            if reg.wrapper == "_mut":
                out.add("StaleEpochError")
        return frozenset(out)

    # -------------------------------------------------- typed raises
    def _infer_raises(self) -> None:
        """Fixpoint over the confident call graph, catch-aware: a call
        inside a ``try`` whose handlers catch T (typed or via parent)
        does not propagate T to this function's raise set."""
        direct: Dict[str, Set[str]] = {}
        # per function: [(callee qn | "rpc:m", caught-name frozenset)]
        prop_calls: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for qn in sorted(self.model.functions):
            fi = self.model.functions[qn]
            info = self.model.modules[fi.module]
            d, calls = self._scan_raises(info, fi)
            direct[qn] = d
            prop_calls[qn] = calls
        raises: Dict[str, Set[str]] = {qn: set(d)
                                       for qn, d in direct.items()}
        changed = True
        while changed:
            changed = False
            for qn in sorted(prop_calls):
                cur = raises[qn]
                for callee, caught in prop_calls[qn]:
                    if callee.startswith("rpc:"):
                        sub: Set[str] = set()
                        method = callee[4:]
                        for reg in self.handlers.get(method, ()):
                            if reg.target:
                                sub |= raises.get(reg.target, set())
                            if reg.wrapper == "_mut":
                                sub.add("StaleEpochError")
                    else:
                        sub = raises.get(callee, set())
                    for t in sub:
                        if t in cur:
                            continue
                        if t in caught or \
                                FT_TYPED_ERRORS[t] & caught:
                            continue
                        cur.add(t)
                        changed = True
        self.raises = {qn: frozenset(s) for qn, s in raises.items()}

    def _scan_raises(self, info: ModuleInfo, fi: FuncInfo
                     ) -> Tuple[Set[str],
                                List[Tuple[str, FrozenSet[str]]]]:
        direct: Set[str] = set()
        calls: List[Tuple[str, FrozenSet[str]]] = []
        # Fast path: without a try-statement the caught-set is empty
        # everywhere — raises and call edges come straight off the
        # (cached) flat walk, no recursive descent.
        has_try = any(isinstance(n, ast.Try)
                      for n in self.model.walk_own(fi.node))
        if not has_try:
            empty: FrozenSet[str] = frozenset()
            for node in self.model.walk_own(fi.node):
                if isinstance(node, ast.Raise) and \
                        node.exc is not None:
                    exc = node.exc
                    f = exc.func if isinstance(exc, ast.Call) else exc
                    ename = f.id if isinstance(f, ast.Name) else \
                        getattr(f, "attr", "")
                    if ename in FT_TYPED_ERRORS:
                        direct.add(ename)
                elif isinstance(node, ast.Call):
                    site = self._call_site_of(fi, node)
                    if site is not None:
                        calls.append((f"rpc:{site.name}", empty))
                    hit = self.model._resolve_call_edge(info, fi,
                                                        node)
                    if hit is not None and \
                            hit[1] in _RAISE_DEPTH_KINDS:
                        calls.append((hit[0], empty))
            return direct, calls

        def scan(nodes, caught: FrozenSet[str]):
            for node in nodes:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                if isinstance(node, ast.Try):
                    body_caught = caught | frozenset(
                        n for h in node.handlers
                        for n in _handler_names(h))
                    scan(node.body, body_caught)
                    for h in node.handlers:
                        scan(h.body, caught)
                    scan(node.orelse, caught)
                    scan(node.finalbody, caught)
                    continue
                if isinstance(node, ast.Raise) and node.exc is not None:
                    exc = node.exc
                    f = exc.func if isinstance(exc, ast.Call) else exc
                    ename = f.id if isinstance(f, ast.Name) else \
                        getattr(f, "attr", "")
                    if ename in FT_TYPED_ERRORS and \
                            ename not in caught and \
                            not (FT_TYPED_ERRORS[ename] & caught):
                        direct.add(ename)
                if isinstance(node, ast.Call):
                    site = self._call_site_of(fi, node)
                    if site is not None:
                        calls.append((f"rpc:{site.name}", caught))
                    hit = self.model._resolve_call_edge(info, fi, node)
                    if hit is not None and \
                            hit[1] in _RAISE_DEPTH_KINDS:
                        calls.append((hit[0], caught))
                scan(ast.iter_child_nodes(node), caught)

        scan(fi.node.body, frozenset())
        return direct, calls

    # ------------------------------------------------------ try sites
    def _scan_tries(self) -> None:
        for qn in sorted(self.model.functions):
            fi = self.model.functions[qn]
            info = self.model.modules[fi.module]
            for node in self.model.walk_own(fi.node):
                if not isinstance(node, ast.Try) or not node.handlers:
                    continue
                site = TrySite(module=fi.module, line=node.lineno,
                               symbol=fi.qualname)
                # Calls under a NESTED try with its own except clauses
                # belong to that inner site, not this one.
                for sub in _walk_no_defs(node.body, skip_tries=True):
                    if not isinstance(sub, ast.Call):
                        continue
                    cs = self._call_site_of(fi, sub)
                    if cs is not None:
                        site.callees.append((f"rpc:{cs.name}",
                                             sub.lineno))
                    hit = self.model._resolve_call_edge(info, fi, sub)
                    if hit is not None and \
                            hit[1] in _RAISE_DEPTH_KINDS:
                        site.callees.append((hit[0], sub.lineno))
                if not site.callees:
                    continue
                for h in node.handlers:
                    names = frozenset(_handler_names(h))
                    bare = (len(h.body) == 1
                            and isinstance(h.body[0], ast.Raise)
                            and h.body[0].exc is None)
                    site.handlers.append((h.lineno, names, bare))
                self.try_sites.append(site)
                for callee, _line in site.callees:
                    for _hl, names, _bare in site.handlers:
                        for t in names & set(FT_TYPED_ERRORS):
                            self.typed_catches.setdefault(
                                (callee, t), []).append(site)

    def callee_raises(self, callee_key: str) -> FrozenSet[str]:
        if callee_key.startswith("rpc:"):
            return self.rpc_raises(callee_key[4:])
        return self.raises.get(callee_key, frozenset())


def _handler_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if t is None:
        return ["BaseException"]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        if isinstance(e, ast.Name):
            out.append(e.id)
        elif isinstance(e, ast.Attribute):
            out.append(e.attr)
    return out


def _walk_no_defs(stmts, skip_tries: bool = False):
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if skip_tries and isinstance(node, ast.Try) and node.handlers:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
