"""``ray_tpu lint`` — the raylint command-line front end.

Exit codes: 0 = clean (no non-baselined findings), 1 = findings,
2 = usage error.  ``--format json`` (alias ``--json``) emits a
machine-readable report for CI gating; ``--format sarif`` emits SARIF
2.1.0 for code-scanning upload (inline PR annotations);
``--update-baseline`` grandfathers the current findings;
``--changed`` scopes REPORTING to git-changed files (the analysis
stays whole-program — interprocedural rules need every file);
``--lock-graph dot|json`` dumps the global lock-order graph;
``--fix`` applies mechanically-safe autofixes (``--diff`` previews
them as a unified diff without writing).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional, Set

from . import (RULE_DOCS, RULES, default_baseline_path,
               default_package_root, run_lint)
from . import baseline as baseline_mod

_SARIF_URI_BASE = "SRCROOT"


def add_lint_parser(sub) -> None:
    """Attach the ``lint`` subcommand to the ray_tpu CLI subparsers."""
    p = sub.add_parser(
        "lint", help="framework-aware static analysis (raylint)")
    p.add_argument("path", nargs="?", default=None,
                   help="package dir to analyze (default: the "
                        "installed ray_tpu package)")
    p.add_argument("--select", default="",
                   help="comma-separated rule subset")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: "
                        "tools/raylint_baseline.json next to the "
                        "package)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings as failures too")
    p.add_argument("--update-baseline", action="store_true",
                   help="write the current findings as the new "
                        "baseline and exit 0")
    p.add_argument("--format", default=None, dest="format",
                   choices=("text", "json", "sarif"),
                   help="report format (default text)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format json")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="report only findings in files changed vs "
                        "REF (default HEAD) per git, plus untracked "
                        "files; the analysis itself stays "
                        "whole-program")
    p.add_argument("--lock-graph", default=None, dest="lock_graph",
                   choices=("dot", "json"),
                   help="dump the global lock-acquisition-order "
                        "graph (nodes, edges with witness sites, "
                        "cycles) and exit")
    p.add_argument("--fix", action="store_true",
                   help="apply mechanically-safe autofixes "
                        "(suppression-comment normalization, eager "
                        "hot-path log formatting -> lazy %%-args) "
                        "and exit")
    p.add_argument("--diff", action="store_true",
                   help="with --fix: print a unified diff instead of "
                        "writing files")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print grandfathered findings")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.set_defaults(fn=cmd_lint)


def _changed_files(root: str, ref: str) -> Optional[Set[str]]:
    """Project-root-relative paths changed vs ``ref`` (tracked diff +
    untracked), or None when git is unusable (caller errors out)."""
    project_dir = os.path.dirname(os.path.abspath(root)) or "."
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=project_dir, capture_output=True, text=True,
            timeout=30)
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=project_dir, capture_output=True, text=True,
            timeout=30)
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=project_dir, capture_output=True, text=True,
            timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    out: Set[str] = set()
    # `git diff --name-only` prints repo-TOPLEVEL-relative paths;
    # findings are project-root relative — rebase when the two
    # differ (monorepo: the package parent need not be the toplevel).
    repo_root = top.stdout.strip() if top.returncode == 0 else ""
    for name in diff.stdout.splitlines():
        if not name:
            continue
        path = os.path.join(repo_root, name) if repo_root else name
        out.add(os.path.relpath(os.path.abspath(path), project_dir))
    # `git ls-files --others` prints CWD-relative paths, and we ran
    # it with cwd=project_dir: they are already in finding shape.
    for name in untracked.stdout.splitlines():
        if name:
            out.add(os.path.normpath(name))
    return out


def _severity(rule: str) -> str:
    """SARIF level: the deadlock/durability classes are errors, the
    hygiene classes warnings."""
    return "error" if rule in (
        "lock-order-inversion", "blocking-under-lock",
        "journaled-mutation", "wait-holding-foreign-lock") \
        else "warning"


def to_sarif(findings, root: str) -> dict:
    """SARIF 2.1.0 (the subset GitHub code scanning renders as inline
    annotations).  ``partialFingerprints`` reuses the baseline
    fingerprint so alert identity survives line shifts, mirroring the
    baseline semantics."""
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": _severity(f.rule),
            "message": {"text": f"{f.symbol}: {f.message}"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/"),
                        "uriBaseId": _SARIF_URI_BASE,
                    },
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {
                "raylint/v1": f.fingerprint,
            },
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                    ".json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "raylint",
                "informationUri":
                    "docs/static_analysis.md",
                "rules": [{
                    "id": name,
                    "shortDescription": {"text": name},
                    "fullDescription": {
                        "text": RULE_DOCS.get(name, "")},
                    "defaultConfiguration": {
                        "level": _severity(name)},
                } for name in sorted(RULES)],
            }},
            "originalUriBaseIds": {
                _SARIF_URI_BASE: {
                    "uri": ("file://"
                            + os.path.dirname(os.path.abspath(root))
                            + "/")}},
            "results": results,
        }],
    }


def cmd_lint(args) -> int:
    if args.list_rules:
        for name in RULES:
            print(f"{name}\n    {RULE_DOCS.get(name, '')}")
        return 0
    fmt = args.format or ("json" if args.as_json else "text")
    if args.update_baseline and args.select:
        # A partial-rule run must never rewrite the whole baseline:
        # it would silently drop every unselected rule's grandfathered
        # entries and fail the next full gate.
        print("raylint: --update-baseline cannot be combined with "
              "--select (a partial run would drop the other rules' "
              "baseline entries)", file=sys.stderr)
        return 2
    if args.update_baseline and args.changed is not None:
        print("raylint: --update-baseline cannot be combined with "
              "--changed (a file-scoped run would drop every other "
              "file's baseline entries)", file=sys.stderr)
        return 2
    if args.diff and not args.fix:
        print("raylint: --diff requires --fix", file=sys.stderr)
        return 2
    root = args.path or default_package_root()
    baseline_path = args.baseline or default_baseline_path(root)
    select = [s.strip() for s in args.select.split(",") if s.strip()]

    if args.fix:
        from . import fixes as fixes_mod

        changed = fixes_mod.compute_fixes(root)
        if args.diff:
            import difflib

            for relpath in sorted(changed):
                old, new = changed[relpath]
                sys.stdout.writelines(difflib.unified_diff(
                    old.splitlines(keepends=True),
                    new.splitlines(keepends=True),
                    fromfile=f"a/{relpath}", tofile=f"b/{relpath}"))
            print(f"raylint: --fix would change "
                  f"{len(changed)} file(s)", file=sys.stderr)
            return 0
        project_dir = os.path.dirname(os.path.abspath(root)) or "."
        for relpath in sorted(changed):
            path = os.path.join(project_dir, relpath)
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(changed[relpath][1])
        print(f"raylint: fixed {len(changed)} file(s)")
        for relpath in sorted(changed):
            print(f"  {relpath}")
        return 0

    if args.lock_graph:
        from .model import ProjectModel

        la = ProjectModel(root).lock_analysis()
        if args.lock_graph == "dot":
            sys.stdout.write(la.to_dot())
        else:
            json.dump(la.to_json(), sys.stdout, indent=1)
            sys.stdout.write("\n")
        return 0

    scope: Optional[Set[str]] = None
    if args.changed is not None:
        scope = _changed_files(root, args.changed)
        if scope is None:
            print(f"raylint: --changed {args.changed}: git diff "
                  f"failed (not a repo, or bad ref)", file=sys.stderr)
            return 2

    t0 = time.monotonic()
    try:
        findings = run_lint(root, select=select or None,
                            baseline_path=baseline_path,
                            use_baseline=not (args.no_baseline
                                              or args.update_baseline))
    except ValueError as e:
        print(f"raylint: {e}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0

    if args.update_baseline:
        n = baseline_mod.save(baseline_path, findings)
        print(f"raylint: baselined {n} finding(s) -> {baseline_path}")
        return 0

    if scope is not None:
        findings = [f for f in findings if f.path in scope]

    fresh = [f for f in findings if not f.baselined]
    old = [f for f in findings if f.baselined]
    if fmt == "json":
        json.dump({
            "root": root,
            "elapsed_s": round(elapsed, 3),
            "counts": {"new": len(fresh), "baselined": len(old)},
            "findings": [f.to_dict() for f in findings],
        }, sys.stdout, indent=1)
        sys.stdout.write("\n")
        return 1 if fresh else 0
    if fmt == "sarif":
        json.dump(to_sarif(fresh, root), sys.stdout, indent=1)
        sys.stdout.write("\n")
        return 1 if fresh else 0

    for f in fresh:
        print(f.render())
    if args.show_baselined:
        for f in old:
            print(f"{f.render()}  [baselined]")
    scoped = "" if scope is None else f" ({len(scope)} changed files)"
    status = (f"raylint: {len(fresh)} finding(s)"
              f" ({len(old)} baselined) over {root}{scoped}"
              f" in {elapsed:.2f}s")
    print(status, file=sys.stderr if fresh else sys.stdout)
    return 1 if fresh else 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="raylint")
    sub = ap.add_subparsers(dest="cmd", required=True)
    add_lint_parser(sub)
    args = ap.parse_args(["lint"] + list(argv or sys.argv[1:]))
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
