"""``ray_tpu lint`` — the raylint command-line front end.

Exit codes: 0 = clean (no non-baselined findings), 1 = findings,
2 = usage error.  ``--json`` emits a machine-readable report for CI
gating; ``--update-baseline`` grandfathers the current findings.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from . import (RULE_DOCS, RULES, default_baseline_path,
               default_package_root, run_lint)
from . import baseline as baseline_mod


def add_lint_parser(sub) -> None:
    """Attach the ``lint`` subcommand to the ray_tpu CLI subparsers."""
    p = sub.add_parser(
        "lint", help="framework-aware static analysis (raylint)")
    p.add_argument("path", nargs="?", default=None,
                   help="package dir to analyze (default: the "
                        "installed ray_tpu package)")
    p.add_argument("--select", default="",
                   help="comma-separated rule subset")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: "
                        "tools/raylint_baseline.json next to the "
                        "package)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings as failures too")
    p.add_argument("--update-baseline", action="store_true",
                   help="write the current findings as the new "
                        "baseline and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable JSON report on stdout")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print grandfathered findings")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.set_defaults(fn=cmd_lint)


def cmd_lint(args) -> int:
    if args.list_rules:
        for name in RULES:
            print(f"{name}\n    {RULE_DOCS.get(name, '')}")
        return 0
    if args.update_baseline and args.select:
        # A partial-rule run must never rewrite the whole baseline:
        # it would silently drop every unselected rule's grandfathered
        # entries and fail the next full gate.
        print("raylint: --update-baseline cannot be combined with "
              "--select (a partial run would drop the other rules' "
              "baseline entries)", file=sys.stderr)
        return 2
    root = args.path or default_package_root()
    baseline_path = args.baseline or default_baseline_path(root)
    select = [s.strip() for s in args.select.split(",") if s.strip()]
    t0 = time.monotonic()
    try:
        findings = run_lint(root, select=select or None,
                            baseline_path=baseline_path,
                            use_baseline=not (args.no_baseline
                                              or args.update_baseline))
    except ValueError as e:
        print(f"raylint: {e}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0

    if args.update_baseline:
        n = baseline_mod.save(baseline_path, findings)
        print(f"raylint: baselined {n} finding(s) -> {baseline_path}")
        return 0

    fresh = [f for f in findings if not f.baselined]
    old = [f for f in findings if f.baselined]
    if args.as_json:
        json.dump({
            "root": root,
            "elapsed_s": round(elapsed, 3),
            "counts": {"new": len(fresh), "baselined": len(old)},
            "findings": [f.to_dict() for f in findings],
        }, sys.stdout, indent=1)
        sys.stdout.write("\n")
        return 1 if fresh else 0

    for f in fresh:
        print(f.render())
    if args.show_baselined:
        for f in old:
            print(f"{f.render()}  [baselined]")
    status = (f"raylint: {len(fresh)} finding(s)"
              f" ({len(old)} baselined) over {root}"
              f" in {elapsed:.2f}s")
    print(status, file=sys.stderr if fresh else sys.stdout)
    return 1 if fresh else 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="raylint")
    sub = ap.add_subparsers(dest="cmd", required=True)
    add_lint_parser(sub)
    args = ap.parse_args(["lint"] + list(argv or sys.argv[1:]))
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
