"""Developer tooling that ships with the package (raylint, ...)."""
