"""The ops CLI: ``python -m ray_tpu <command>``.

Reference: python/ray/scripts/scripts.py (ray start/stop/status/
timeline/memory, A.4) + the state CLI (util/state/state_cli.py:
``ray list ...``) + the job CLI (dashboard/modules/job/cli.py).

Commands:
  start --head [--port P] [--storage PATH]      run a head (blocking)
  start --address H:P [--num-cpus N] [...]      run a worker node
  status --address H:P                          cluster summary
                                                (+ per-node device HBM)
  top --address H:P [--once] [--interval S]     live cluster view
                                                (HBM/occupancy/queues)
  dashboard --address H:P [--port 8265]         web dashboard
  client-proxy --address H:P [--port 10001]     thin-driver proxy
  list (nodes|actors|jobs|tasks|objects) ...    state listings
  timeline --address H:P -o trace.json          Chrome-trace export
  metrics (query|names|alerts) --address H:P    windowed TSDB queries
                                                + alert states
  memory --address H:P                          object-store stats
  postmortem [INCIDENT] --address H:P           incident forensics:
             [--capture] [-o trace.json]        death reports + merged
                                                crash traces
  job (submit|status|logs|stop|list) ...        job control
  lint [PATH] [--format json|sarif] [--changed] [--lock-graph dot|json]
       [--update-baseline]                      raylint static analysis
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _connect(address: str):
    import ray_tpu

    return ray_tpu.init(address=address, num_cpus=0)


def cmd_start(args) -> int:
    if args.head:
        from ray_tpu.cluster.head import HeadServer

        head = HeadServer(args.host, args.port,
                          storage_path=args.storage or None)
        print(f"RAY_TPU_HEAD_ADDRESS={head.address}", flush=True)
        print("To connect: ray_tpu.init(address="
              f"\"{head.address}\")", flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            return 0
    if not args.address:
        print("start needs --head or --address", file=sys.stderr)
        return 2
    from ray_tpu.cluster import worker_main

    argv = ["--head", args.address]
    if args.num_cpus is not None:
        argv += ["--num-cpus", str(args.num_cpus)]
    if args.resources:
        argv += ["--resources", args.resources]
    if args.name:
        argv += ["--name", args.name]
    return worker_main.main(argv)


def cmd_status(args) -> int:
    rt = _connect(args.address)
    nodes = rt.cluster.list_nodes()
    alive = [n for n in nodes if n["alive"]]
    print(f"{len(alive)}/{len(nodes)} nodes alive")
    totals, avail = {}, {}
    for n in alive:
        for k, v in n["total"].items():
            totals[k] = totals.get(k, 0) + v
        for k, v in n["available"].items():
            avail[k] = avail.get(k, 0) + v
    for k in sorted(totals):
        if k == "memory":
            print(f"  {k}: {avail.get(k, 0)/1e9:.1f}/"
                  f"{totals[k]/1e9:.1f} GB available")
        elif "_group_" not in k:
            print(f"  {k}: {avail.get(k, 0):g}/{totals[k]:g} available")
    actors = rt.cluster.head.call("list_actors", {})
    print(f"{len(actors)} registered actors")
    crashed = [n for n in nodes if n.get("crashes")]
    if crashed:
        print("crashes (death reports per node):")
        for n in crashed:
            label = n.get("name") or n["node_id"][:12]
            print(f"  {label}: {n['crashes']}")
    _print_device_summary(rt, nodes)
    return 0


def _query_by_node(rt, expr: str):
    """{node_id: value} for one head TSDB expression grouped by
    node_id; {} when the head has no matching history (device plane
    idle, jax never imported, pre-TSDB head)."""
    try:
        resp = rt.cluster.head.call("metrics_query", {"expr": expr},
                                    timeout=15.0)
        return {r["labels"].get("node_id", ""): r["value"]
                for r in resp["rows"]}
    except Exception:  # raylint: disable=ft-exception-swallow -- any-failure → empty column is the design: status/top must render on clusters with no TSDB rows (or a pre-TSDB head)
        return {}


def _fmt_gb(v) -> str:
    if v is None:
        return "-"
    return f"{v / 1e9:.2f}G" if v >= 1e8 else f"{v / 1e6:.1f}M"


def _print_device_summary(rt, nodes) -> None:
    """The per-node device column of ``ray_tpu status``: HBM
    used/limit + live buffers from the shipped device-plane series
    (observability/device.py).  Silent when no node ever sampled a
    device — status must not regress on jax-free clusters."""
    used = _query_by_node(rt, "last(ray_tpu_device_hbm_bytes_used)"
                              "[120s] by (node_id)")
    if not used:
        return
    limit = _query_by_node(rt, "last(ray_tpu_device_hbm_bytes_limit)"
                               "[120s] by (node_id)")
    bufs = _query_by_node(rt, "last(ray_tpu_device_live_buffers)"
                              "[120s] by (node_id)")
    print("device hbm (used/limit, live buffers):")
    by_id = {n["node_id"]: n for n in nodes}
    for nid in sorted(used):
        n = by_id.get(nid, {})
        label = n.get("name") or nid[:12]
        lim = limit.get(nid)
        lim_s = _fmt_gb(lim) if lim else "?"
        print(f"  {label}: hbm {_fmt_gb(used[nid])}/{lim_s} "
              f"buffers {bufs.get(nid, 0):g}")


def cmd_list(args) -> int:
    rt = _connect(args.address)
    node = getattr(args, "node", None) or None
    state_f = getattr(args, "state", None) or None
    trace_id = getattr(args, "trace_id", None) or None
    if args.what == "nodes":
        rows = rt.cluster.list_nodes()
        if node:
            rows = [n for n in rows if n["node_id"].startswith(node)
                    or n.get("name") == node]
    elif args.what == "actors":
        # Filters apply at the HEAD, not here (state API predicate
        # pushdown — the reply ships only matching rows).
        rows = rt.cluster.head.call(
            "list_actors", {"node": node, "state": state_f})
        for r in rows:
            r["actor_id"] = r["actor_id"].hex()[:16]
    elif args.what == "jobs":
        from ray_tpu import job as job_mod

        rows = job_mod.list_jobs()
        if state_f:
            rows = [j for j in rows
                    if j.get("status") == state_f.upper()]
    elif args.what == "tasks":
        # Task/object tables are per-node runtime state; the head has
        # no global view (reference: the state API aggregates via
        # per-node agents).  Gather over the nodes' RPC servers;
        # trace/state filters ship WITH the RPC and apply node-side.
        rows = _gather_node_state(
            rt, "tasks", node=node,
            filters={"trace_id": trace_id, "state": state_f})
    elif args.what == "objects":
        rows = _gather_node_state(rt, "objects", node=node)
    elif args.what == "artifacts":
        # Profile artifacts in the head store (device-trace zips):
        # names here feed `profile --device -o` downloads and
        # /api/profile?device=1&artifact=<name>.
        rows = rt.cluster.head.call("list_artifacts", {},
                                    timeout=15.0)
        if node:
            rows = [a for a in rows
                    if str(a.get("node_id", "")).startswith(node)]
    else:
        print(f"unknown listing {args.what!r}", file=sys.stderr)
        return 2
    print(json.dumps(rows, indent=2, default=str))
    return 0


def _gather_node_state(rt, what: str, node=None, filters=None):
    """Per-node task/object state over the node RPC servers (the
    driver's own runtime is empty — it just connected).  ``node``
    restricts which nodes are asked at all; ``filters`` ride the RPC
    and are applied by the node before its reply ships."""
    out = []
    filters = {k: v for k, v in (filters or {}).items()
               if v is not None}
    for n in rt.cluster.list_nodes():
        if not n.get("alive"):
            continue
        if node and not (n["node_id"].startswith(node)
                         or n.get("name") == node):
            continue
        try:
            resp = rt.cluster.pool.get(n["address"]).call(
                "node_state", {"what": what, "filters": filters},
                timeout=30.0)
            out.append({"node": n.get("name") or n["node_id"][:12],
                        what: resp})
        except Exception as e:  # noqa: BLE001
            out.append({"node": n.get("name") or n["node_id"][:12],
                        "error": str(e)})
    return out


def cmd_timeline(args) -> int:
    _connect(args.address)
    # The MERGED cluster export — the CLI process just connected, so
    # its own local buffer is empty; the story lives in the head's
    # per-node stores.
    from ray_tpu.observability.events import export_cluster_timeline

    path = export_cluster_timeline(args.output)
    print(f"wrote {path}")
    return 0


def cmd_memory(args) -> int:
    rt = _connect(args.address)
    print(json.dumps({
        "local_store": rt.object_store.stats(),
        "plasma": rt.plasma.stats(),
    }, indent=2))
    return 0


def cmd_postmortem(args) -> int:
    """Incident forensics (observability/postmortem.py):

    - ``postmortem`` — list recent death reports;
    - ``postmortem --capture`` — snapshot + bundle the live cluster's
      flight records without a death (pre-crash baseline);
    - ``postmortem <incident>`` — merge that incident's bundle with
      the surviving cluster timeline/logs into one Chrome trace
      (``--out``, Perfetto-loadable) + a printed report."""
    rt = _connect(args.address)
    from ray_tpu.observability import postmortem as pm

    head_call = rt.cluster.head.call
    if args.capture:
        report = pm.capture_incident(head_call)
        print(f"captured {report['incident']} "
              f"({report['processes']} process records) -> "
              f"artifact {report['artifact']}")
        if not args.incident:
            args.incident = report["incident"]
    if not args.incident:
        resp = head_call("list_death_reports", {"limit": args.limit})
        reports = resp.get("reports", [])
        if not reports:
            print("no death reports")
            return 0
        for r in reports:
            node = str(r.get("node_id", ""))[:12] or "-"
            print(f"{r.get('incident', '?')}  {r.get('cause', '?')}"
                  f"  node {node}  pid {r.get('pid', '-')}"
                  + ("  [oom]" if r.get("oom") else ""))
        return 0
    merged = pm.merge_incident(head_call, args.incident,
                               window_s=args.window)
    print(pm.render_report(merged["report"]))
    out = args.out or f"postmortem-{args.incident}.trace.json"
    with open(out, "w") as f:
        json.dump({"traceEvents": merged["trace"]}, f)
    print(f"wrote {out} ({len(merged['trace'])} events) — "
          f"load in Perfetto / chrome://tracing")
    return 0


def cmd_logs(args) -> int:
    """Three modes (reference: ``ray logs`` + the log monitor's
    driver-routed streams, log_monitor.py:103):

    - ``logs <node>`` — legacy raw tail of that node's log file;
    - ``logs --trace <id> [--node/--actor/--level/...]`` — structured
      query, filtered SERVER-SIDE at the head (``cluster_logs``);
    - ``logs -f`` — follow mode: stream records to the driver as the
      head ingests them (the ``logs`` pubsub channel)."""
    rt = _connect(args.address)
    from ray_tpu.observability import logs as logs_mod

    # ANY structured filter selects structured mode — `logs <node>
    # --level ERROR` must not silently drop the level filter and
    # return the raw tail; the positional then acts as --node.
    structured = bool(args.trace or args.follow or args.level
                      or args.actor or args.grep or args.node)
    if args.node_tail and not structured:
        return _tail_node_file(rt, args.node_tail, args.bytes)
    filters = {k: v for k, v in {
        "trace_id": args.trace, "node": args.node or args.node_tail,
        "actor": args.actor, "level": args.level,
        "text": args.grep,
    }.items() if v}
    if args.follow:
        try:
            for rec in logs_mod.follow(rt.cluster, **filters):
                print(logs_mod.format_record(rec), flush=True)
        except KeyboardInterrupt:
            return 0
        return 0
    records = logs_mod.query_cluster(rt.cluster, limit=args.limit,
                                     **filters)
    for rec in records:
        print(logs_mod.format_record(rec))
    if not records:
        print("(no matching records)", file=sys.stderr)
    return 0


def _tail_node_file(rt, node: str, tail_bytes: int) -> int:
    for n in rt.cluster.list_nodes():
        if not (n["node_id"].startswith(node)
                or n.get("name") == node):
            continue
        if not n["alive"]:
            print(f"node {node!r} is dead; its log file lives on "
                  f"that host's --log-dir", file=sys.stderr)
            return 1
        try:
            resp = rt.cluster.pool.get(n["address"]).call(
                "tail_log", {"bytes": tail_bytes}, timeout=30.0)
        except Exception as e:  # noqa: BLE001
            print(f"node {node!r} unreachable: {e}",
                  file=sys.stderr)
            return 1
        if not resp.get("found"):
            print("(node has no log file — started without "
                  "--log-dir)", file=sys.stderr)
            return 1
        sys.stdout.write(resp["data"])
        return 0
    print(f"no node matching {node!r}", file=sys.stderr)
    return 1


def cmd_profile(args) -> int:
    """On-demand sampling profile of a node process or an actor
    (reference: the reporter module's profile_manager endpoints) —
    collapsed-stack flamegraph text by default, Chrome-trace JSON
    with --chrome (mergeable with `ray_tpu timeline` output)."""
    rt = _connect(args.address)
    thread_filter = args.thread or None
    target_node = args.node or None
    if args.actor:
        # Resolve the actor to its node; its executor threads are
        # named "actor-<name>..." so the sampler can isolate them.
        found = {}
        for ns in ([args.namespace] if args.namespace
                   else ["default", ""]):
            found = rt.cluster.head.call(
                "lookup_named_actor", {"name": args.actor,
                                       "namespace": ns},
                timeout=10.0)
            if found.get("found"):
                break
        if not found.get("found"):
            print(f"no actor named {args.actor!r}", file=sys.stderr)
            return 1
        target_node = found["node_id"]
        thread_filter = thread_filter or f"actor-{args.actor}"
    rpc = "device_trace" if args.device else "profile"
    payload = ({"duration_s": args.duration,
                # -o: bytes ride the capture reply (one transfer, no
                # race against head-store eviction).
                "inline": bool(args.output)} if args.device else
               {"duration_s": args.duration,
                "interval_s": args.interval,
                "thread_filter": thread_filter})
    prof = None
    for n in rt.cluster.list_nodes():
        if target_node and not (n["node_id"].startswith(target_node)
                                or n.get("name") == target_node):
            continue
        if not target_node and n["node_id"] != rt.cluster.node_id:
            continue
        prof = rt.cluster.pool.get(n["address"]).call(
            rpc, payload, timeout=args.duration + 60.0)
        break
    if prof is None:
        print(f"no node matching {target_node!r}", file=sys.stderr)
        return 1
    if args.device:
        # The capture shipped its zipped trace bundle to the head's
        # artifact store; -o additionally downloads it here.
        print(f"device trace {prof['name']}: {prof['bytes']} bytes, "
              f"{prof['files']} files, node {prof['node_id'][:12]} "
              f"(fetch: /api/profile?device=1&artifact={prof['name']})")
        if args.output:
            with open(args.output, "wb") as f:
                f.write(prof["data"])
            print(f"wrote {args.output}")
        return 0
    body = (json.dumps(prof["chrome"]) if args.chrome
            else prof["collapsed"])
    if args.output:
        with open(args.output, "w") as f:
            f.write(body)
        print(f"wrote {args.output} ({prof['num_samples']} samples, "
              f"{len(prof['threads'])} threads)")
    else:
        print(body)
    return 0


def cmd_metrics(args) -> int:
    """Windowed queries over the head's metrics TSDB + the alert
    plane (docs/observability.md has the query-language cookbook):

    - ``metrics query 'p99(ray_tpu_channel_write_wait_seconds)[30s]
      by (node_id)'`` — evaluate one expression against the shipped
      history;
    - ``metrics names`` — stored series names + store stats;
    - ``metrics alerts`` — declared rules and pending/firing
      instances."""
    rt = _connect(args.address)
    head = rt.cluster.head
    if args.metrics_cmd == "query":
        try:
            resp = head.call("metrics_query", {"expr": args.expr},
                             timeout=30.0)
        except ValueError as e:
            print(f"query error: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(resp, indent=2, default=str))
            return 0
        rows = resp["rows"]
        for row in rows:
            labels = ",".join(f"{k}={v}" for k, v in
                              sorted(row["labels"].items()))
            print(f"{{{labels}}} {row['value']:.6g}")
        if not rows:
            print("(no matching series in the window)",
                  file=sys.stderr)
        return 0
    if args.metrics_cmd == "names":
        resp = head.call("metrics_query", {"names": True},
                         timeout=30.0)
        for name in resp["names"]:
            print(name)
        print(json.dumps(resp["stats"]), file=sys.stderr)
        return 0
    if args.metrics_cmd == "alerts":
        resp = head.call("alerts_status", {}, timeout=30.0)
        if args.json:
            print(json.dumps(resp, indent=2, default=str))
            return 0
        for st in resp["active"]:
            labels = ",".join(f"{k}={v}" for k, v in
                              sorted(st["labels"].items()))
            print(f"{st['state'].upper():8s} {st['rule']} "
                  f"{{{labels}}} value={st.get('value')}")
        if not resp["active"]:
            print("(no pending or firing alerts)")
        print(f"{len(resp['rules'])} rules declared",
              file=sys.stderr)
        return 0
    return 2


def _top_snapshot(rt):
    """One data frame for ``ray_tpu top``: node table + actor counts
    + the device/model-plane series, all grouped by node_id (every
    read is one head RPC — the view costs the cluster a handful of
    TSDB queries per refresh, not a per-node fanout)."""
    nodes = rt.cluster.list_nodes()
    actors: dict = {}
    try:
        for a in rt.cluster.head.call("list_actors", {},
                                      timeout=15.0):
            if a.get("state", "ALIVE") == "ALIVE":
                nid = str(a.get("node_id", ""))
                actors[nid] = actors.get(nid, 0) + 1
    except Exception:  # raylint: disable=ft-exception-swallow -- the actor column degrades to 0s rather than killing the live view mid-refresh
        pass
    q = lambda expr: _query_by_node(rt, expr)  # noqa: E731
    return {
        "nodes": nodes,
        "actors": actors,
        "hbm_used": q("last(ray_tpu_device_hbm_bytes_used)[120s] "
                      "by (node_id)"),
        "hbm_limit": q("last(ray_tpu_device_hbm_bytes_limit)[120s] "
                       "by (node_id)"),
        "bufs": q("last(ray_tpu_device_live_buffers)[120s] "
                  "by (node_id)"),
        "xla": q("increase(ray_tpu_xla_compiles_total)[60s] "
                 "by (node_id)"),
        "occupancy": q("last(ray_tpu_decode_batch_occupancy)[60s] "
                       "by (node_id)"),
        "qdepth": q("last(ray_tpu_queue_depth)[60s] by (node_id)"),
        "train_tps": q("last(ray_tpu_train_tokens_per_s)[60s] "
                       "by (node_id)"),
        "incidents": _recent_incidents(rt),
    }


def _recent_incidents(rt, limit: int = 5):
    """Newest death reports for the ``top`` incidents lane ([] on a
    pre-postmortem head)."""
    try:
        resp = rt.cluster.head.call("list_death_reports",
                                    {"limit": limit}, timeout=15.0)
        return resp.get("reports", [])
    except Exception:  # raylint: disable=ft-exception-swallow -- the incidents lane degrades to empty rather than killing the live view
        return []


def render_top(snap) -> str:
    """Render one ``ray_tpu top`` frame as a fixed-column table
    (pure: the render smoke test feeds it synthetic snapshots)."""
    cols = ["NODE", "STATE", "ACTORS", "HBM USED/LIMIT", "BUFS",
            "XLA/60s", "DECODE OCC", "QDEPTH", "TRAIN TOK/S"]
    rows = []
    for n in snap["nodes"]:
        nid = n["node_id"]
        used = snap["hbm_used"].get(nid)
        limit = snap["hbm_limit"].get(nid)
        hbm = "-"
        if used is not None:
            hbm = _fmt_gb(used) + "/" + (_fmt_gb(limit) if limit
                                         else "?")
        fmt = lambda d, g="%g": (  # noqa: E731
            "-" if d.get(nid) is None else g % d[nid])
        rows.append([
            (n.get("name") or nid[:12]),
            "ALIVE" if n.get("alive") else "DEAD",
            str(snap["actors"].get(nid, 0)),
            hbm,
            fmt(snap["bufs"]),
            fmt(snap["xla"], "%.0f"),
            fmt(snap["occupancy"], "%.0f"),
            fmt(snap["qdepth"], "%.0f"),
            fmt(snap["train_tps"], "%.0f"),
        ])
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows
              else len(c) for i, c in enumerate(cols)]
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    alive = sum(1 for n in snap["nodes"] if n.get("alive"))
    lines.append(f"{alive}/{len(snap['nodes'])} nodes alive · "
                 f"{sum(snap['actors'].values())} actors · "
                 f"{time.strftime('%H:%M:%S')}")
    incidents = snap.get("incidents") or []
    if incidents:
        lines.append("INCIDENTS (newest first):")
        for r in incidents:
            node = str(r.get("node_id", ""))[:12] or "-"
            lines.append(
                f"  {r.get('incident', '?')}  {r.get('cause', '?')}"
                f"  node {node}  pid {r.get('pid', '-')}"
                + ("  [oom]" if r.get("oom") else ""))
    return "\n".join(lines)


def cmd_top(args) -> int:
    """Live cluster view: nodes x actors x HBM x decode occupancy x
    queue depth, polling the head TSDB (``--once`` prints a single
    frame for scripts/CI)."""
    rt = _connect(args.address)
    if args.once:
        print(render_top(_top_snapshot(rt)))
        return 0
    try:
        while True:
            frame = render_top(_top_snapshot(rt))
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_dashboard(args) -> int:
    """Attach to the cluster and serve the web dashboard
    (dashboard/head.py:61 analogue) until interrupted."""
    _connect(args.address)
    from ray_tpu.dashboard import start_dashboard

    dash = start_dashboard(args.host, args.port)
    print(f"dashboard at {dash.url} (ctrl-c to stop)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        dash.shutdown()
    return 0


def cmd_client_proxy(args) -> int:
    """Attach to the cluster and host thin remote drivers
    (util/client/server/proxier.py analogue) until interrupted."""
    _connect(args.address)
    from ray_tpu.util.client import ClientProxyServer

    srv = ClientProxyServer(args.host, args.port)
    print(f"client proxy at {srv.address} (ctrl-c to stop)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


def cmd_job(args) -> int:
    from ray_tpu import job as job_mod

    _connect(args.address)
    if args.job_cmd == "submit":
        runtime_env = json.loads(args.runtime_env) \
            if args.runtime_env else None
        job_id = job_mod.submit_job(args.entrypoint,
                                    runtime_env=runtime_env)
        print(job_id)
        if args.wait:
            status = job_mod.wait_job(job_id, timeout=args.timeout)
            print(status)
            return 0 if status == "SUCCEEDED" else 1
        return 0
    if args.job_cmd == "status":
        print(job_mod.get_job_status(args.job_id))
        return 0
    if args.job_cmd == "logs":
        print(job_mod.get_job_logs(args.job_id))
        return 0
    if args.job_cmd == "stop":
        print(job_mod.stop_job(args.job_id))
        return 0
    if args.job_cmd == "list":
        print(json.dumps(job_mod.list_jobs(), indent=2, default=str))
        return 0
    return 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ray_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--storage", default="",
                   help="head: persistence file (GCS fault tolerance)")
    p.add_argument("--address", default="")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--resources", default="")
    p.add_argument("--name", default="")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status", help="cluster summary")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser(
        "top", help="live cluster view (nodes x actors x HBM x "
                    "decode occupancy x queue depth, via the head "
                    "TSDB)")
    p.add_argument("--address", required=True)
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (scripts/CI)")
    p.add_argument("--interval", type=float, default=2.0)
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--address", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("client-proxy",
                       help="host thin remote drivers")
    p.add_argument("--address", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10001)
    p.set_defaults(fn=cmd_client_proxy)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("what", choices=["nodes", "actors", "jobs",
                                    "tasks", "objects", "artifacts"])
    p.add_argument("--address", required=True)
    p.add_argument("--trace-id", default="",
                   help="tasks: only rows of this distributed trace "
                        "(applied node-side)")
    p.add_argument("--node", default="",
                   help="node id prefix or name filter "
                        "(applied server-side)")
    p.add_argument("--state", default="",
                   help="actors/jobs/tasks: state filter, e.g. ALIVE "
                        "/ PENDING / SUCCEEDED")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("timeline", help="export Chrome trace")
    p.add_argument("--address", required=True)
    p.add_argument("-o", "--output", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("memory", help="object store stats")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser(
        "postmortem",
        help="incident forensics: list death reports, capture a "
             "live bundle, or merge one into a Chrome trace")
    p.add_argument("incident", nargs="?", default="",
                   help="incident id to merge (omit to list)")
    p.add_argument("--address", required=True)
    p.add_argument("--capture", action="store_true",
                   help="bundle the live cluster's flight records "
                        "now (no death required)")
    p.add_argument("--window", type=float, default=60.0,
                   help="merge: seconds of surviving-cluster "
                        "history around the crash")
    p.add_argument("-o", "--out", default="",
                   help="merge: trace output path (default "
                        "postmortem-<incident>.trace.json)")
    p.add_argument("--limit", type=int, default=20,
                   help="list mode: reports to show")
    p.set_defaults(fn=cmd_postmortem)

    p = sub.add_parser(
        "logs", help="structured cluster logs (query/follow) or a "
                     "node's raw log tail")
    p.add_argument("node_tail", nargs="?", default="",
                   metavar="node",
                   help="node id prefix or name: raw file tail mode")
    p.add_argument("--address", required=True)
    p.add_argument("--bytes", type=int, default=64 * 1024,
                   help="raw tail mode: bytes to fetch")
    p.add_argument("--trace", default="",
                   help="only records of this trace id (the "
                        "cross-process correlation query)")
    p.add_argument("--node", default="",
                   help="only records shipped by this node "
                        "(id prefix)")
    p.add_argument("--actor", default="",
                   help="only records from this actor (id prefix)")
    p.add_argument("--level", default="",
                   type=lambda s: s.upper(),
                   choices=["", "DEBUG", "INFO", "WARNING", "ERROR",
                            "CRITICAL"],
                   help="minimum level (DEBUG/INFO/WARNING/ERROR)")
    p.add_argument("--grep", default="",
                   help="message substring filter")
    p.add_argument("--limit", type=int, default=1000)
    p.add_argument("-f", "--follow", action="store_true",
                   help="stream new records to this terminal")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser(
        "metrics",
        help="windowed metric queries + alert states (head TSDB)")
    msub = p.add_subparsers(dest="metrics_cmd", required=True)
    mq = msub.add_parser(
        "query", help="evaluate 'fn(metric{label=v})[window] "
                      "by (label)' over the shipped history")
    mq.add_argument("expr")
    mq.add_argument("--address", required=True)
    mq.add_argument("--json", action="store_true",
                    help="full JSON response instead of one row "
                         "per line")
    mn = msub.add_parser("names",
                         help="stored series names + store stats")
    mn.add_argument("--address", required=True)
    ma = msub.add_parser(
        "alerts", help="declared rules + pending/firing instances")
    ma.add_argument("--address", required=True)
    ma.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "profile", help="sampling profile of a node or actor "
                        "(collapsed-stack flamegraph text)")
    p.add_argument("--address", required=True)
    p.add_argument("--node", default="",
                   help="node id prefix or name (default: the "
                        "driver-attached node)")
    p.add_argument("--actor", default="",
                   help="profile the node hosting this named actor, "
                        "filtered to its executor threads")
    p.add_argument("--namespace", default="")
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("--interval", type=float, default=0.01)
    p.add_argument("--thread", default="",
                   help="thread-name substring filter")
    p.add_argument("--chrome", action="store_true",
                   help="emit Chrome-trace JSON instead of "
                        "collapsed stacks")
    p.add_argument("--device", action="store_true",
                   help="capture a DEVICE trace instead "
                        "(jax.profiler start/stop_trace on the "
                        "target node; the zipped TensorBoard bundle "
                        "ships to the head artifact store)")
    p.add_argument("-o", "--output", default="",
                   help="write to a file instead of stdout "
                        "(--device: download the trace zip here)")
    p.set_defaults(fn=cmd_profile)

    from ray_tpu.tools.raylint.cli import add_lint_parser

    add_lint_parser(sub)

    p = sub.add_parser("job", help="job control")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("entrypoint")
    js.add_argument("--address", required=True)
    js.add_argument("--runtime-env", default="")
    js.add_argument("--wait", action="store_true")
    js.add_argument("--timeout", type=float, default=600.0)
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("job_id")
        jp.add_argument("--address", required=True)
    jl = jsub.add_parser("list")
    jl.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_job)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
