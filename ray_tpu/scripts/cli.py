"""The ops CLI: ``python -m ray_tpu <command>``.

Reference: python/ray/scripts/scripts.py (ray start/stop/status/
timeline/memory, A.4) + the state CLI (util/state/state_cli.py:
``ray list ...``) + the job CLI (dashboard/modules/job/cli.py).

Commands:
  start --head [--port P] [--storage PATH]      run a head (blocking)
  start --address H:P [--num-cpus N] [...]      run a worker node
  status --address H:P                          cluster summary
  dashboard --address H:P [--port 8265]         web dashboard
  client-proxy --address H:P [--port 10001]     thin-driver proxy
  list (nodes|actors|jobs|tasks|objects) ...    state listings
  timeline --address H:P -o trace.json          Chrome-trace export
  memory --address H:P                          object-store stats
  job (submit|status|logs|stop|list) ...        job control
  lint [PATH] [--json] [--update-baseline]      raylint static analysis
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _connect(address: str):
    import ray_tpu

    return ray_tpu.init(address=address, num_cpus=0)


def cmd_start(args) -> int:
    if args.head:
        from ray_tpu.cluster.head import HeadServer

        head = HeadServer(args.host, args.port,
                          storage_path=args.storage or None)
        print(f"RAY_TPU_HEAD_ADDRESS={head.address}", flush=True)
        print("To connect: ray_tpu.init(address="
              f"\"{head.address}\")", flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            return 0
    if not args.address:
        print("start needs --head or --address", file=sys.stderr)
        return 2
    from ray_tpu.cluster import worker_main

    argv = ["--head", args.address]
    if args.num_cpus is not None:
        argv += ["--num-cpus", str(args.num_cpus)]
    if args.resources:
        argv += ["--resources", args.resources]
    if args.name:
        argv += ["--name", args.name]
    return worker_main.main(argv)


def cmd_status(args) -> int:
    rt = _connect(args.address)
    nodes = rt.cluster.list_nodes()
    alive = [n for n in nodes if n["alive"]]
    print(f"{len(alive)}/{len(nodes)} nodes alive")
    totals, avail = {}, {}
    for n in alive:
        for k, v in n["total"].items():
            totals[k] = totals.get(k, 0) + v
        for k, v in n["available"].items():
            avail[k] = avail.get(k, 0) + v
    for k in sorted(totals):
        if k == "memory":
            print(f"  {k}: {avail.get(k, 0)/1e9:.1f}/"
                  f"{totals[k]/1e9:.1f} GB available")
        elif "_group_" not in k:
            print(f"  {k}: {avail.get(k, 0):g}/{totals[k]:g} available")
    actors = rt.cluster.head.call("list_actors", {})
    print(f"{len(actors)} registered actors")
    return 0


def cmd_list(args) -> int:
    rt = _connect(args.address)
    if args.what == "nodes":
        rows = rt.cluster.list_nodes()
    elif args.what == "actors":
        rows = rt.cluster.head.call("list_actors", {})
        for r in rows:
            r["actor_id"] = r["actor_id"].hex()[:16]
    elif args.what == "jobs":
        from ray_tpu import job as job_mod

        rows = job_mod.list_jobs()
    elif args.what == "tasks":
        # Task/object tables are per-node runtime state; the head has
        # no global view (reference: the state API aggregates via
        # per-node agents).  Gather over the nodes' RPC servers.
        rows = _gather_node_state(rt, "tasks")
    elif args.what == "objects":
        rows = _gather_node_state(rt, "objects")
    else:
        print(f"unknown listing {args.what!r}", file=sys.stderr)
        return 2
    print(json.dumps(rows, indent=2, default=str))
    return 0


def _gather_node_state(rt, what: str):
    """Per-node task/object state over the node RPC servers (the
    driver's own runtime is empty — it just connected)."""
    out = []
    for n in rt.cluster.list_nodes():
        if not n.get("alive"):
            continue
        try:
            resp = rt.cluster.pool.get(n["address"]).call(
                "node_state", {"what": what}, timeout=30.0)
            out.append({"node": n.get("name") or n["node_id"][:12],
                        what: resp})
        except Exception as e:  # noqa: BLE001
            out.append({"node": n.get("name") or n["node_id"][:12],
                        "error": str(e)})
    return out


def cmd_timeline(args) -> int:
    _connect(args.address)
    # The MERGED cluster export — the CLI process just connected, so
    # its own local buffer is empty; the story lives in the head's
    # per-node stores.
    from ray_tpu.observability.events import export_cluster_timeline

    path = export_cluster_timeline(args.output)
    print(f"wrote {path}")
    return 0


def cmd_memory(args) -> int:
    rt = _connect(args.address)
    print(json.dumps({
        "local_store": rt.object_store.stats(),
        "plasma": rt.plasma.stats(),
    }, indent=2))
    return 0


def cmd_logs(args) -> int:
    rt = _connect(args.address)
    for n in rt.cluster.list_nodes():
        if not (n["node_id"].startswith(args.node)
                or n.get("name") == args.node):
            continue
        if not n["alive"]:
            print(f"node {args.node!r} is dead; its log file lives on "
                  f"that host's --log-dir", file=sys.stderr)
            return 1
        try:
            resp = rt.cluster.pool.get(n["address"]).call(
                "tail_log", {"bytes": args.bytes}, timeout=30.0)
        except Exception as e:  # noqa: BLE001
            print(f"node {args.node!r} unreachable: {e}",
                  file=sys.stderr)
            return 1
        if not resp.get("found"):
            print("(node has no log file — started without "
                  "--log-dir)", file=sys.stderr)
            return 1
        sys.stdout.write(resp["data"])
        return 0
    print(f"no node matching {args.node!r}", file=sys.stderr)
    return 1


def cmd_dashboard(args) -> int:
    """Attach to the cluster and serve the web dashboard
    (dashboard/head.py:61 analogue) until interrupted."""
    _connect(args.address)
    from ray_tpu.dashboard import start_dashboard

    dash = start_dashboard(args.host, args.port)
    print(f"dashboard at {dash.url} (ctrl-c to stop)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        dash.shutdown()
    return 0


def cmd_client_proxy(args) -> int:
    """Attach to the cluster and host thin remote drivers
    (util/client/server/proxier.py analogue) until interrupted."""
    _connect(args.address)
    from ray_tpu.util.client import ClientProxyServer

    srv = ClientProxyServer(args.host, args.port)
    print(f"client proxy at {srv.address} (ctrl-c to stop)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


def cmd_job(args) -> int:
    from ray_tpu import job as job_mod

    _connect(args.address)
    if args.job_cmd == "submit":
        runtime_env = json.loads(args.runtime_env) \
            if args.runtime_env else None
        job_id = job_mod.submit_job(args.entrypoint,
                                    runtime_env=runtime_env)
        print(job_id)
        if args.wait:
            status = job_mod.wait_job(job_id, timeout=args.timeout)
            print(status)
            return 0 if status == "SUCCEEDED" else 1
        return 0
    if args.job_cmd == "status":
        print(job_mod.get_job_status(args.job_id))
        return 0
    if args.job_cmd == "logs":
        print(job_mod.get_job_logs(args.job_id))
        return 0
    if args.job_cmd == "stop":
        print(job_mod.stop_job(args.job_id))
        return 0
    if args.job_cmd == "list":
        print(json.dumps(job_mod.list_jobs(), indent=2, default=str))
        return 0
    return 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ray_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--storage", default="",
                   help="head: persistence file (GCS fault tolerance)")
    p.add_argument("--address", default="")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--resources", default="")
    p.add_argument("--name", default="")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status", help="cluster summary")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--address", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("client-proxy",
                       help="host thin remote drivers")
    p.add_argument("--address", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10001)
    p.set_defaults(fn=cmd_client_proxy)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("what", choices=["nodes", "actors", "jobs",
                                    "tasks", "objects"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("timeline", help="export Chrome trace")
    p.add_argument("--address", required=True)
    p.add_argument("-o", "--output", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("memory", help="object store stats")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("logs", help="tail a node's log file")
    p.add_argument("node", help="node id prefix or name")
    p.add_argument("--address", required=True)
    p.add_argument("--bytes", type=int, default=64 * 1024)
    p.set_defaults(fn=cmd_logs)

    from ray_tpu.tools.raylint.cli import add_lint_parser

    add_lint_parser(sub)

    p = sub.add_parser("job", help="job control")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("entrypoint")
    js.add_argument("--address", required=True)
    js.add_argument("--runtime-env", default="")
    js.add_argument("--wait", action="store_true")
    js.add_argument("--timeout", type=float, default=600.0)
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("job_id")
        jp.add_argument("--address", required=True)
    jl = jsub.add_parser("list")
    jl.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_job)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
