"""ray_tpu: a TPU-native distributed AI framework.

Ray-equivalent capabilities (see SURVEY.md for the reference blueprint),
built TPU-first: tasks/actors/objects orchestrate *processes and hosts*;
jax/XLA (pjit over device meshes, Pallas kernels, ICI/DCN collectives)
owns the chip-level compute.  Public surface mirrors python/ray/__init__.py:
``init/shutdown/remote/get/put/wait/cancel/kill`` plus the libraries
(``ray_tpu.data``, ``.train``, ``.tune``, ``.serve``; an RLlib
equivalent is not built yet).
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Optional, Sequence, Union

from ._version import version as __version__
from . import exceptions
from .core.actor import ActorClass, ActorHandle, ActorMethod, exit_actor
from .core.config import GLOBAL_CONFIG
from .core.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from .core.object_ref import ObjectRef, ObjectRefGenerator
from .core.remote_function import RemoteFunction
from .core import runtime as _runtime_mod
from .core.runtime import (get_runtime, is_initialized, try_get_runtime)
from .core.task_spec import (DefaultSchedulingStrategy,
                             NodeAffinitySchedulingStrategy,
                             NodeLabelSchedulingStrategy,
                             PlacementGroupSchedulingStrategy,
                             SpreadSchedulingStrategy)

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "cancel", "kill", "get_actor", "method", "exit_actor", "nodes",
    "cluster_resources", "available_resources", "get_runtime_context",
    "ObjectRef", "ObjectRefGenerator", "ActorClass", "ActorHandle",
    "exceptions", "timeline", "__version__",
]


def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: Optional[str] = None,
         runtime_env: Optional[dict] = None,
         ignore_reinit_error: bool = False,
         log_to_driver: bool = True,
         _system_config: Optional[Dict[str, Any]] = None,
         **kwargs):
    """Start (or connect to) the runtime.

    Reference: ray.init (python/ray/_private/worker.py:1270).  With no
    address this boots an in-process head (local node, scheduler, object
    store).  ``address="auto"``/host:port attaches to a running cluster
    (ray_tpu.core.node, cluster mode).
    """
    if is_initialized():
        if ignore_reinit_error:
            return get_runtime()
        raise RuntimeError(
            "ray_tpu.init() called twice — pass ignore_reinit_error=True "
            "to allow")
    if _system_config:
        GLOBAL_CONFIG.update(_system_config)
    if address not in (None, "local"):
        from .core.node import connect_to_cluster

        return connect_to_cluster(address, namespace=namespace or "",
                                  runtime_env=runtime_env,
                                  num_cpus=num_cpus, num_tpus=num_tpus,
                                  resources=resources)
    return _runtime_mod.init_runtime(
        num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
        namespace=namespace or "", runtime_env=runtime_env)


def shutdown():
    _runtime_mod.shutdown_runtime()


def _auto_init():
    if not is_initialized():
        _runtime_mod.init_runtime()
    return get_runtime()


def remote(*args, **kwargs):
    """Decorator converting a function into a RemoteFunction or a class
    into an ActorClass (reference: worker.py:3352)."""

    def make(target, options):
        if inspect.isclass(target):
            return ActorClass(target, options)
        if not callable(target):
            raise TypeError(f"@remote target must be callable, got "
                            f"{type(target)}")
        return RemoteFunction(target, options)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return make(args[0], {})
    if args:
        raise TypeError("@remote accepts only keyword options, e.g. "
                        "@remote(num_cpus=2)")
    return lambda target: make(target, kwargs)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    return _auto_init().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return _auto_init().put(value)


def wait(refs, *, num_returns: int = 1, timeout: Optional[float] = None,
         fetch_local: bool = True):
    return _auto_init().wait(refs, num_returns=num_returns, timeout=timeout,
                             fetch_local=fetch_local)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    get_runtime().cancel(ref, force=force, recursive=recursive)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    if not isinstance(actor, ActorHandle):
        raise TypeError(f"kill() expects an ActorHandle, got {type(actor)}")
    get_runtime().kill_actor(actor._actor_id, no_restart=no_restart)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    rt = get_runtime()
    ns = namespace if namespace is not None else rt.namespace
    actor_id = rt.actor_manager.get_named(name, ns)
    if actor_id is None:
        if rt.cluster is not None:
            found = rt.cluster.lookup_named_actor(name, ns)
            if found is not None:
                aid_bytes, klass, _node, _addr = found
                return ActorHandle(ActorID(aid_bytes), klass, rt)
        raise ValueError(
            f"no actor named {name!r} in namespace {ns!r}")
    return rt.actor_manager.get_handle(actor_id)


def method(**options):
    """Per-method default options decorator (reference: ray.method)."""

    def decorator(fn):
        fn.__ray_tpu_method_options__ = options
        return fn

    return decorator


def get_runtime_context():
    return get_runtime().runtime_context


def nodes():
    rt = get_runtime()
    if rt.cluster is not None:
        return [{
            "NodeID": n["node_id"], "Alive": n["alive"],
            "Resources": n["total"], "alive": n["alive"],
            "NodeManagerAddress": n["address"],
        } for n in rt.cluster.list_nodes()]
    return [{
        "NodeID": rt.node_id.hex(),
        "Alive": True,
        "Resources": rt.node_resources.total,
        "alive": True,
    }]


def _sum_view(rt, key: str) -> Dict[str, float]:
    """Aggregate over the heartbeat-synced resource view (ray_syncer
    role: no head RPC on the hot path); falls back to one list_nodes
    RPC when the view is stale."""
    view = rt.cluster.resource_view()
    total: Dict[str, float] = {}
    if view is not None:
        for rec in view.values():
            if rec["alive"]:
                for k, v in rec.get(key, {}).items():
                    total[k] = total.get(k, 0) + v
        return total
    for n in rt.cluster.list_nodes():
        if n["alive"]:
            for k, v in n[key].items():
                total[k] = total.get(k, 0) + v
    return total


def cluster_resources() -> Dict[str, float]:
    rt = get_runtime()
    if rt.cluster is not None:
        return _sum_view(rt, "total")
    return rt.node_resources.total


def available_resources() -> Dict[str, float]:
    rt = get_runtime()
    if rt.cluster is not None:
        return _sum_view(rt, "available")
    return rt.node_resources.available()


def timeline(filename: Optional[str] = None):
    """Chrome-trace export of task events (reference: ray.timeline,
    _private/state.py:948).  In cluster mode this is the MERGED
    cluster timeline: every node's shipped events in one trace, one
    pid lane per process, with flow arrows stitching cross-process
    ring edges."""
    from .observability.events import export_cluster_timeline

    return export_cluster_timeline(filename)
