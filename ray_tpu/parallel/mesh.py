"""Device-mesh construction with named parallelism axes.

TPU-first replacement for the reference's process-group bootstrap
(train/torch/config.py:66 ``_setup_torch_process_group``): instead of a
rank/world NCCL group, parallelism is expressed as a
``jax.sharding.Mesh`` whose named axes carry the strategy:

==========  ============================================================
axis        meaning
==========  ============================================================
``data``    pure data parallelism (gradients psum'd over it)
``fsdp``    data parallelism with parameter/optimizer sharding (ZeRO-3);
            weights are sharded over it and all-gathered per layer
``pipe``    pipeline stages (inter-slice over DCN on multi-slice pods)
``tensor``  megatron-style tensor parallelism (heads/mlp sharded)
``seq``     sequence/context parallelism (ring attention axis)
``expert``  MoE expert parallelism (ragged all_to_all dispatch axis)
==========  ============================================================

Axis order matters: the last axes change fastest over the physical
device list, so ``tensor``/``seq`` (highest-bandwidth collectives) sit
innermost to ride ICI, while ``pipe``/``data`` sit outermost where DCN
hops are tolerable (scaling-book layout recipe).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Outer-to-inner physical ordering (see module docstring).
AXIS_ORDER: Tuple[str, ...] = ("pipe", "data", "fsdp", "expert", "seq",
                               "tensor")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: one int per parallelism axis.

    ``MeshSpec(fsdp=-1)`` lets one axis absorb all remaining devices
    (like a -1 in a reshape).
    """

    data: int = 1
    fsdp: int = 1
    pipe: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {name: getattr(self, name) for name in AXIS_ORDER}

    def resolved(self, n_devices: int) -> "MeshSpec":
        """Resolve a single -1 axis against ``n_devices``."""
        sizes = self.axis_sizes()
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wild}")
        if wild:
            fixed = math.prod(v for v in sizes.values() if v != -1)
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed} ({sizes})")
            sizes[wild[0]] = n_devices // fixed
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {total} devices, have {n_devices}")
        return MeshSpec(**sizes)

    @property
    def n_devices(self) -> int:
        sizes = self.axis_sizes()
        if any(v == -1 for v in sizes.values()):
            raise ValueError("unresolved -1 axis; call resolved() first")
        return math.prod(sizes.values())

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        return build_mesh(self, devices)

    @classmethod
    def auto(cls, n_devices: int, *, tensor: int = 1, seq: int = 1,
             pipe: int = 1, expert: int = 1, fsdp: bool = True) -> "MeshSpec":
        """Fill the leftover devices into fsdp (default) or data."""
        spec = cls(tensor=tensor, seq=seq, pipe=pipe, expert=expert,
                   fsdp=-1 if fsdp else 1, data=1 if fsdp else -1)
        return spec.resolved(n_devices)


def build_mesh(spec: MeshSpec,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Materialize a ``jax.sharding.Mesh`` for ``spec``.

    Devices are laid out row-major over ``AXIS_ORDER`` so the innermost
    axes map to physically adjacent devices.  On real TPU slices
    ``jax.devices()`` is already ordered by torus coordinates, which
    keeps ``tensor``/``seq`` collectives on nearest-neighbor ICI links.
    """
    if devices is None:
        devices = jax.devices()
    spec = spec.resolved(len(devices))
    sizes = spec.axis_sizes()
    dev_array = np.asarray(devices, dtype=object).reshape(
        tuple(sizes[a] for a in AXIS_ORDER))
    return Mesh(dev_array, AXIS_ORDER)


def get_abstract_mesh(spec: MeshSpec,
                      n_devices: Optional[int] = None
                      ) -> jax.sharding.AbstractMesh:
    """Shape-only mesh for tracing/compile-ahead without real devices.

    Pass ``n_devices`` to resolve a -1 wildcard axis; otherwise the
    spec must be fully specified.
    """
    if n_devices is not None:
        spec = spec.resolved(n_devices)
    sizes = spec.axis_sizes()  # raises on unresolved -1 via n_devices
    if any(v == -1 for v in sizes.values()):
        raise ValueError("spec has a -1 axis; pass n_devices")
    return jax.sharding.AbstractMesh(
        tuple(sizes[a] for a in AXIS_ORDER), AXIS_ORDER)


def local_mesh() -> Mesh:
    """Single-device mesh (all axes size 1) — the degenerate case used
    for single-chip runs and tests."""
    return build_mesh(MeshSpec(), jax.devices()[:1])
