"""Logical-axis sharding rules.

Models annotate arrays with *logical* axis names ("batch", "embed",
"heads", …); a rule table maps those to mesh axes.  Swapping the rule
table re-shards the whole model (DP↔FSDP↔TP↔…) without touching model
code — the t5x/flax-partitioning idea, self-contained here.

The reference has no analogue (its TP/SP slots are empty, SURVEY.md
§2.3); this is the TPU-native mechanism that fills them.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions: new jax exposes it at the
    top level with ``check_vma``; 0.4.x has
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm

    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=check_vma)


# A logical axis maps to: one mesh axis, a tuple of mesh axes (the dim
# is sharded over their product), or None (replicated).
Rule = Tuple[str, Union[str, Tuple[str, ...], None]]


class ShardingRules:
    """Ordered logical-axis → mesh-axis mapping."""

    def __init__(self, *rules: Rule):
        self._table = dict(rules)

    def mesh_axes(self, logical: Optional[str]):
        if logical is None:
            return None
        return self._table.get(logical)

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        """PartitionSpec for a tuple of per-dim logical names.

        A mesh axis may appear at most once across the dims of one
        array; later duplicates fall back to replication.
        """
        used = set()
        parts = []
        for name in logical_axes:
            axes = self.mesh_axes(name)
            if axes is None:
                parts.append(None)
                continue
            if isinstance(axes, str):
                axes = (axes,)
            axes = tuple(a for a in axes if a not in used)
            used.update(axes)
            if not axes:
                parts.append(None)
            elif len(axes) == 1:
                parts.append(axes[0])
            else:
                parts.append(axes)
        while parts and parts[-1] is None:
            parts.pop()
        return P(*parts)

    def extended(self, *rules: Rule) -> "ShardingRules":
        new = ShardingRules()
        new._table = {**self._table, **dict(rules)}
        return new


# Default rules for transformer LMs (scaling-book recipe):
#  - activations: batch over (data, fsdp); seq over seq (context
#    parallel); heads/mlp over tensor.
#  - weights: embed dim over fsdp (ZeRO-3 gather per layer), output
#    feature dims over tensor (megatron), experts over expert.
#  - "layers" shards a lax.scan-stacked weight tree over pipe stages.
DEFAULT_RULES = ShardingRules(
    ("batch", ("data", "fsdp")),
    ("seq", "seq"),
    ("act_embed", "tensor"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("head_dim", None),
    ("embed", "fsdp"),
    ("mlp", "tensor"),
    ("vocab", "tensor"),
    ("expert", "expert"),
    ("layers", "pipe"),
)


class _Ctx(threading.local):
    def __init__(self):
        self.mesh: Optional[Mesh] = None
        self.rules: ShardingRules = DEFAULT_RULES


_ctx = _Ctx()


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    # Thread-local only: NamedSharding carries its mesh, so no jax-global
    # mesh context is required (and jax 0.9 renamed that API anyway).
    prev = _ctx.mesh
    _ctx.mesh = mesh
    try:
        yield mesh
    finally:
        _ctx.mesh = prev


@contextlib.contextmanager
def use_sharding_rules(rules: ShardingRules):
    prev = _ctx.rules
    _ctx.rules = rules
    try:
        yield rules
    finally:
        _ctx.rules = prev


def current_mesh() -> Optional[Mesh]:
    return _ctx.mesh


def current_rules() -> ShardingRules:
    return _ctx.rules


def logical_sharding(logical_axes: Sequence[Optional[str]],
                     mesh: Optional[Mesh] = None,
                     rules: Optional[ShardingRules] = None) -> NamedSharding:
    mesh = mesh or _ctx.mesh
    if mesh is None:
        raise ValueError("no mesh: pass one or enter use_mesh(...)")
    rules = rules or _ctx.rules
    return NamedSharding(mesh, rules.spec(logical_axes))


@contextlib.contextmanager
def suppress_constraints():
    """Disable with_logical_constraint within the block — used while
    tracing code placed inside a fully-manual shard_map region, where
    global sharding constraints don't apply (the shard_map specs own
    the layout)."""
    prev = getattr(_ctx, "suppress", False)
    _ctx.suppress = True
    try:
        yield
    finally:
        _ctx.suppress = prev


def with_logical_constraint(x, *logical_axes: Optional[str],
                            rules: Optional[ShardingRules] = None):
    """``lax.with_sharding_constraint`` by logical axis names.

    No-op outside a mesh context so model code runs unchanged on a
    single device (tests, single-chip bench), and under
    suppress_constraints() (inside shard_map bodies).
    """
    mesh = _ctx.mesh
    if mesh is None or mesh.size == 1 or getattr(_ctx, "suppress", False):
        return x
    rules = rules or _ctx.rules
    spec = rules.spec(logical_axes)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def shard_params(params, logical_axes_tree, mesh: Optional[Mesh] = None,
                 rules: Optional[ShardingRules] = None):
    """Device-put a param pytree according to a matching pytree of
    logical-axis tuples (None leaves replicate)."""
    mesh = mesh or _ctx.mesh
    rules = rules or _ctx.rules

    def place(x, axes):
        if mesh is None:
            return x
        spec = rules.spec(axes) if axes is not None else P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, params, logical_axes_tree,
                        is_leaf=lambda v: v is None)
