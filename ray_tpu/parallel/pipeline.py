"""Pipeline parallelism: GPipe microbatch schedule over the ``pipe``
mesh axis.

Reference: the reference ships NO pipeline training schedule — its
compiled-graph substrate (dag/dag_node_operation.py:506-539 overlap
schedules, NCCL p2p channels) is the intended building block and the
TPU build must supply the strategy natively (SURVEY §2.3).

TPU-first design: the schedule is a single jitted program, not an
actor choreography.  Each pipe rank holds a contiguous slice of the
stacked layer weights (the existing ("layers", "pipe") sharding rule);
``shard_map`` runs the per-stage code; activations move stage→stage
with ``lax.ppermute`` over the ICI ring; the tick loop is a
``lax.scan``.  Differentiating through it yields the reverse pipeline
automatically (ppermute transposes to the reverse ring) — GPipe
semantics: all-forward then all-backward per microbatch set, bubble
fraction (P-1)/(M+P-1) each way.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

PyTree = Any


def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """Idle fraction of the GPipe schedule (per direction)."""
    return (num_stages - 1) / (num_microbatches + num_stages - 1)


def pipeline_layers(layer_fn: Callable[[jax.Array, PyTree], jax.Array],
                    stacked_params: PyTree, x: jax.Array, *,
                    mesh: Mesh, num_microbatches: int,
                    pipe_axis: str = "pipe",
                    batch_axes=()) -> jax.Array:
    """Apply L stacked layers to ``x`` (B, S, E), layer-sharded into
    P = mesh.shape[pipe_axis] stages with an M-microbatch GPipe
    schedule.  ``layer_fn(h, layer_slice) -> h`` applies ONE layer (any
    remat wrapping included).  ``batch_axes``: mesh axes the microbatch
    batch dim is sharded over (data parallel composes with pp).

    The whole mesh is manualized (a partial-manual variant that leaves
    fsdp/tensor compiler-managed inside stages hangs XLA:CPU compiles
    as of jax 0.9); a stage therefore holds its L/P layers gathered —
    fine at the scales pipe stages target today, revisit for
    fsdp-inside-pp at 8B+."""
    n_pipe = mesh.shape[pipe_axis]
    M = num_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % n_pipe:
        raise ValueError(f"{L} layers not divisible by pipe={n_pipe}")
    x_mb = x.reshape(M, B // M, *x.shape[1:])

    batch_spec = tuple(batch_axes) if batch_axes else None
    x_spec = P(None, batch_spec, *(None,) * (x.ndim - 1))
    param_spec = jax.tree.map(lambda _: P(pipe_axis), stacked_params)

    from .sharding import shard_map, suppress_constraints

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_spec, x_spec), out_specs=x_spec,
        check_vma=False)
    def run(local_layers, xmb):
        idx = jax.lax.axis_index(pipe_axis)
        T = M + n_pipe - 1

        def apply_local(h):
            def body(h, layer):
                # Global sharding constraints don't apply inside the
                # fully-manual region; the shard_map specs own layout.
                with suppress_constraints():
                    return layer_fn(h, layer), None

            h, _ = jax.lax.scan(body, h, local_layers)
            return h

        perm = [(i, (i + 1) % n_pipe) for i in range(n_pipe)]

        def tick(carry, t):
            prev_out, outputs = carry
            recv = jax.lax.ppermute(prev_out, pipe_axis, perm)
            x_t = xmb[jnp.clip(t, 0, M - 1)]
            # Stage 0 feeds from the microbatch stream; later stages
            # from their predecessor's previous-tick output.
            inp = jnp.where(idx == 0, x_t, recv)
            out = apply_local(inp)
            # The last stage emits microbatch t-(P-1) at tick t.
            store = jnp.clip(t - (n_pipe - 1), 0, M - 1)
            valid = (t >= n_pipe - 1).astype(out.dtype)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                outputs[store] * (1 - valid) + out * valid,
                store, 0)
            return (out, outputs), None

        outputs0 = jnp.zeros_like(xmb)
        carry0 = (jnp.zeros_like(xmb[0]), outputs0)
        (last, outputs), _ = jax.lax.scan(
            tick, carry0, jnp.arange(T, dtype=jnp.int32))
        # Every rank stored its own stage outputs; only the last
        # stage's are the pipeline's. Zero the rest and share over the
        # pipe ring so downstream (head/loss) stays replicated.
        outputs = jnp.where(idx == n_pipe - 1, outputs, 0)
        outputs = jax.lax.psum(outputs, pipe_axis)
        return outputs

    out_mb = run(stacked_params, x_mb)
    return out_mb.reshape(B, *x.shape[1:])
