"""TPU-native parallelism layer.

The reference (zszheng/ray) is an orchestration layer that delegates
chip-level parallelism to external engines (SURVEY.md §2.3: TP/PP/SP/EP
"Not implemented" — torch DDP/FSDP wrappers only, reference
train/torch/train_loop_utils.py:162-188).  On TPU there is nothing to
delegate to, so parallelism is first-class here:

- :class:`MeshSpec` — named device-mesh axes (data/fsdp/pipe/tensor/
  seq/expert) over ``jax.sharding.Mesh`` (ICI intra-slice, DCN
  inter-slice).
- Logical-axis sharding rules (:mod:`ray_tpu.parallel.sharding`) map
  model-level axis names ("batch", "embed", "heads", …) to mesh axes;
  ``with_logical_constraint`` annotates activations inside jit.
- :mod:`ray_tpu.parallel.collective` — ray.util.collective-shaped group
  API (reference util/collective/collective.py:120) whose device path
  lowers to XLA collectives (psum/all_gather/reduce_scatter/all_to_all)
  instead of NCCL.
"""

from .mesh import MeshSpec, build_mesh, get_abstract_mesh, local_mesh
from .sharding import (
    ShardingRules,
    DEFAULT_RULES,
    logical_sharding,
    use_sharding_rules,
    with_logical_constraint,
    shard_params,
    current_mesh,
    use_mesh,
)

__all__ = [
    "MeshSpec",
    "build_mesh",
    "local_mesh",
    "get_abstract_mesh",
    "ShardingRules",
    "DEFAULT_RULES",
    "logical_sharding",
    "use_sharding_rules",
    "with_logical_constraint",
    "shard_params",
    "current_mesh",
    "use_mesh",
]
