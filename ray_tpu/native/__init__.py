"""Native (C++) runtime components, loaded via ctypes.

The compute path is jax/XLA/pallas; the runtime around it uses C++
where the reference's runtime does (SURVEY §2.1 N19/N23).  Modules
here compile lazily with the system toolchain into a per-user cache
and degrade loudly (ImportError with the compiler output) if the
toolchain is missing.
"""

from .channel import Channel, ChannelClosed  # noqa: F401

__all__ = ["Channel", "ChannelClosed"]
