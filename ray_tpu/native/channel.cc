// Mutable shared-memory channel: the compiled-DAG data plane.
//
// Reference: src/ray/core_worker/experimental_mutable_object_manager.h
// (:48 WriteAcquire/WriteRelease, :153 ReadAcquire/ReadRelease) and its
// Python face, python/ray/experimental/channel/shared_memory_channel.py
// :159 — pre-allocated mutable buffers with acquire/release semantics
// so a compiled DAG's repeated passes reuse ONE allocation instead of
// minting an object per tick.
//
// Design: a single-producer single-consumer ring of fixed-size slots in
// a POSIX shm file.  Synchronization is a pthread mutex + condvar pair
// with PTHREAD_PROCESS_SHARED set, living in the mapping's header (the
// reference uses the same pthread-in-shm technique).  The producer
// blocks when the ring is full (backpressure), the consumer when it is
// empty.  Peer death is detected two ways, both on the blocking paths
// (not just the close flag): the robust mutex surfaces EOWNERDEAD when
// a holder dies mid-critical-section, and each side records its pid in
// the header at open so a blocked wait can probe the peer process
// (kill(pid, 0)) between condvar slices and return -ECONNRESET instead
// of sleeping out the full timeout against a corpse.
//
// Build: g++ -O2 -shared -fPIC channel.cc -o libray_tpu_channel.so
// (the Python wrapper compiles this lazily and loads it with ctypes —
// no pybind11 in the image).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52544348414E4E32ULL;  // "RTCHANN2"

// Blocked waits wake at least this often to probe peer liveness.
constexpr double kProbeSliceS = 0.2;

struct Header {
  uint64_t magic;
  uint64_t n_slots;
  uint64_t slot_bytes;
  pthread_mutex_t mu;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
  uint64_t write_idx;   // next slot the producer fills
  uint64_t read_idx;    // next slot the consumer drains
  uint32_t closed;      // either side closed
  uint32_t _pad;
  uint64_t writer_pid;  // recorded at open; 0 = side never attached
  uint64_t reader_pid;
  uint64_t lengths[];   // per-slot payload length
};

struct Chan {
  Header* h;
  uint8_t* slots;
  size_t map_bytes;
  int writable;
};

size_t total_bytes(uint64_t n_slots, uint64_t slot_bytes) {
  return sizeof(Header) + n_slots * sizeof(uint64_t) +
         n_slots * slot_bytes;
}

uint8_t* slot_base(Header* h) {
  return reinterpret_cast<uint8_t*>(h) + sizeof(Header) +
         h->n_slots * sizeof(uint64_t);
}

void abs_deadline(timespec* ts, double timeout_s) {
  // MONOTONIC: a wall-clock step (NTP) must not stretch or spuriously
  // expire blocked waits (condvars are initialized with the same
  // clock below).
  clock_gettime(CLOCK_MONOTONIC, ts);
  ts->tv_sec += static_cast<time_t>(timeout_s);
  ts->tv_nsec +=
      static_cast<long>((timeout_s - static_cast<time_t>(timeout_s)) * 1e9);
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

double now_mono() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + ts.tv_nsec * 1e-9;
}

}  // namespace

extern "C" {

// Create the channel backing file and initialize the header.
// Returns 0 on success, -errno on failure.
int rtchan_create(const char* path, uint64_t n_slots,
                  uint64_t slot_bytes) {
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return -errno;
  size_t bytes = total_bytes(n_slots, slot_bytes);
  if (ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
    int e = errno;
    close(fd);
    unlink(path);
    return -e;
  }
  void* mem = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    unlink(path);
    return -errno;
  }
  Header* h = static_cast<Header*>(mem);
  std::memset(h, 0, sizeof(Header));
  h->n_slots = n_slots;
  h->slot_bytes = slot_bytes;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  // Robust: a holder dying with the lock leaves it recoverable
  // instead of deadlocking the peer.
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&h->not_full, &ca);
  pthread_cond_init(&h->not_empty, &ca);
  pthread_condattr_destroy(&ca);

  h->magic = kMagic;  // last: marks init complete
  msync(mem, sizeof(Header), MS_SYNC);
  munmap(mem, bytes);
  return 0;
}

// Open an existing channel.  Returns an opaque handle or null.
void* rtchan_open(const char* path, int writable) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) {
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  // Record this side's pid so the peer's blocked waits can probe our
  // liveness (single producer / single consumer: one pid per side).
  if (writable) {
    h->writer_pid = static_cast<uint64_t>(getpid());
  } else {
    h->reader_pid = static_cast<uint64_t>(getpid());
  }
  Chan* c = new Chan;
  c->h = h;
  c->slots = slot_base(h);
  c->map_bytes = static_cast<size_t>(st.st_size);
  c->writable = writable;
  return c;
}

// 1 if the OTHER side attached and its process no longer exists.  A
// same-pid ring (both endpoints in one process, e.g. in-process actors)
// never reports a dead peer — thread death is the actor runtime's to
// detect.
static int peer_is_dead(Chan* c) {
  uint64_t pid =
      c->writable ? c->h->reader_pid : c->h->writer_pid;
  if (pid == 0 || pid == static_cast<uint64_t>(getpid())) return 0;
  return kill(static_cast<pid_t>(pid), 0) == -1 && errno == ESRCH;
}

static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    // Previous holder died mid-critical-section; state is still
    // consistent for our ring (indices advance after writes).
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// pthread_cond_timedwait re-acquires the robust mutex, so it too can
// surface EOWNERDEAD; failing to mark the mutex consistent there
// would poison it (ENOTRECOVERABLE) on the next unlock — exactly the
// permanent wedge robustness exists to prevent.
static int timedwait_robust(pthread_cond_t* cv, Header* h,
                            const timespec* ts) {
  int rc = pthread_cond_timedwait(cv, &h->mu, ts);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

// Block until the ring has data (reader) / a free slot (writer), with
// the mutex held on entry AND exit.  Waits in <= kProbeSliceS slices,
// probing the peer process between slices — a peer dying mid-pass
// surfaces as -ECONNRESET in one slice instead of a full-timeout hang.
// Returns 0 (condition holds), -EPIPE (closed), -ETIMEDOUT, or
// -ECONNRESET (peer process gone).
static int wait_ring(Chan* c, int for_reader, double timeout_s) {
  Header* h = c->h;
  double deadline = now_mono() + timeout_s;
  while (for_reader ? (h->read_idx == h->write_idx)
                    : (h->write_idx - h->read_idx >= h->n_slots)) {
    if (h->closed) return -EPIPE;
    double left = deadline - now_mono();
    if (left <= 0) return -ETIMEDOUT;
    if (peer_is_dead(c)) return -ECONNRESET;
    timespec ts;
    abs_deadline(&ts, left < kProbeSliceS ? left : kProbeSliceS);
    timedwait_robust(for_reader ? &h->not_empty : &h->not_full, h, &ts);
  }
  return 0;
}

// Producer: wait for a free slot, copy payload in, publish.
// Returns 0, -ETIMEDOUT, -EPIPE (closed), -ECONNRESET (reader process
// died), -EMSGSIZE, or -errno.
int rtchan_put(void* chan, const uint8_t* data, uint64_t len,
               double timeout_s) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (len > h->slot_bytes) return -EMSGSIZE;
  if (lock_robust(h) != 0) return -EINVAL;
  int rc = wait_ring(c, /*for_reader=*/0, timeout_s);
  if (rc == 0 && h->closed) rc = -EPIPE;
  if (rc != 0) {
    pthread_mutex_unlock(&h->mu);
    return rc;
  }
  uint64_t slot = h->write_idx % h->n_slots;
  // Copy OUTSIDE the lock would race the consumer's release; with one
  // producer the slot is exclusively ours while unpublished, so drop
  // the lock during the (possibly large) memcpy.
  pthread_mutex_unlock(&h->mu);
  std::memcpy(c->slots + slot * h->slot_bytes, data, len);
  if (lock_robust(h) != 0) return -EINVAL;
  h->lengths[slot] = len;
  h->write_idx += 1;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Consumer: wait for a sealed slot; copies payload into out (cap
// out_cap) and releases the slot.  Returns payload length, -ETIMEDOUT,
// -EPIPE (closed AND drained), -ECONNRESET (writer process died), or
// -EMSGSIZE if out_cap is too small (slot is NOT released so the
// caller can retry with a bigger buffer).
int64_t rtchan_get(void* chan, uint8_t* out, uint64_t out_cap,
                   double timeout_s) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (lock_robust(h) != 0) return -EINVAL;
  int wrc = wait_ring(c, /*for_reader=*/1, timeout_s);
  if (wrc != 0) {
    pthread_mutex_unlock(&h->mu);
    return wrc;
  }
  uint64_t slot = h->read_idx % h->n_slots;
  uint64_t len = h->lengths[slot];
  if (len > out_cap) {
    pthread_mutex_unlock(&h->mu);
    return -EMSGSIZE;
  }
  // Single consumer: the slot stays ours until we advance read_idx.
  pthread_mutex_unlock(&h->mu);
  std::memcpy(out, c->slots + slot * h->slot_bytes, len);
  if (lock_robust(h) != 0) return -EINVAL;
  h->read_idx += 1;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

// Peek the next payload length without consuming (-EPIPE / -ETIMEDOUT
// as in rtchan_get, 0 timeout = non-blocking probe returning -EAGAIN).
int64_t rtchan_next_len(void* chan, double timeout_s) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (lock_robust(h) != 0) return -EINVAL;
  if (timeout_s <= 0 && h->read_idx == h->write_idx) {
    int empty_rc = h->closed ? -EPIPE : -EAGAIN;
    pthread_mutex_unlock(&h->mu);
    return empty_rc;
  }
  int wrc = wait_ring(c, /*for_reader=*/1, timeout_s);
  if (wrc != 0) {
    pthread_mutex_unlock(&h->mu);
    return wrc;
  }
  int64_t len =
      static_cast<int64_t>(h->lengths[h->read_idx % h->n_slots]);
  pthread_mutex_unlock(&h->mu);
  return len;
}

// In-place slot access (SPSC makes it safe: the writer owns an
// unpublished slot exclusively, the reader owns the head slot until it
// advances read_idx).  The Python adapter assembles/parses frames
// directly in slot memory — one memcpy per side instead of three.

// Wait for a free slot and return its base pointer, or null with
// *err = -ETIMEDOUT / -EPIPE / -EINVAL.  Caller writes <= slot_bytes
// then calls rtchan_write_commit(len).
uint8_t* rtchan_write_begin(void* chan, double timeout_s, int64_t* err) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (lock_robust(h) != 0) { *err = -EINVAL; return nullptr; }
  int wrc = wait_ring(c, /*for_reader=*/0, timeout_s);
  if (wrc == 0 && h->closed) wrc = -EPIPE;
  if (wrc != 0) {
    pthread_mutex_unlock(&h->mu);
    *err = wrc;
    return nullptr;
  }
  uint64_t slot = h->write_idx % h->n_slots;
  pthread_mutex_unlock(&h->mu);
  *err = 0;
  return c->slots + slot * h->slot_bytes;
}

int rtchan_write_commit(void* chan, uint64_t len) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (len > h->slot_bytes) return -EMSGSIZE;
  if (lock_robust(h) != 0) return -EINVAL;
  uint64_t slot = h->write_idx % h->n_slots;
  h->lengths[slot] = len;
  h->write_idx += 1;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Wait for a sealed slot; returns its base pointer with *len_or_err =
// payload length, or null with *len_or_err = -ETIMEDOUT / -EPIPE /
// -EINVAL.  The slot stays valid until rtchan_read_commit.
uint8_t* rtchan_read_begin(void* chan, double timeout_s,
                           int64_t* len_or_err) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (lock_robust(h) != 0) { *len_or_err = -EINVAL; return nullptr; }
  int wrc = wait_ring(c, /*for_reader=*/1, timeout_s);
  if (wrc != 0) {
    pthread_mutex_unlock(&h->mu);
    *len_or_err = wrc;
    return nullptr;
  }
  uint64_t slot = h->read_idx % h->n_slots;
  *len_or_err = static_cast<int64_t>(h->lengths[slot]);
  pthread_mutex_unlock(&h->mu);
  return c->slots + slot * h->slot_bytes;
}

int rtchan_read_commit(void* chan) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (lock_robust(h) != 0) return -EINVAL;
  h->read_idx += 1;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Geometry getters: the adapter layer sizes frames against the slot
// capacity (oversize payloads fall back to the object plane per-pass).
int64_t rtchan_slot_bytes(void* chan) {
  return static_cast<int64_t>(static_cast<Chan*>(chan)->h->slot_bytes);
}

int64_t rtchan_n_slots(void* chan) {
  return static_cast<int64_t>(static_cast<Chan*>(chan)->h->n_slots);
}

// Test hook: take the shared mutex and DON'T release it.  A process
// calling this then dying exercises the robust-mutex recovery path
// (EOWNERDEAD → pthread_mutex_consistent) from a real peer death.
int rtchan_debug_lock(void* chan) {
  return lock_robust(static_cast<Chan*>(chan)->h);
}

// Non-blocking peer-liveness probe for the adapter layer (the same
// check the blocked waits run between condvar slices).
int rtchan_peer_dead(void* chan) {
  return peer_is_dead(static_cast<Chan*>(chan));
}

int rtchan_size(void* chan) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (lock_robust(h) != 0) return -EINVAL;
  int n = static_cast<int>(h->write_idx - h->read_idx);
  pthread_mutex_unlock(&h->mu);
  return n;
}

void rtchan_close(void* chan) {
  Chan* c = static_cast<Chan*>(chan);
  Header* h = c->h;
  if (lock_robust(h) == 0) {
    h->closed = 1;
    pthread_cond_broadcast(&h->not_empty);
    pthread_cond_broadcast(&h->not_full);
    pthread_mutex_unlock(&h->mu);
  }
}

void rtchan_free(void* chan) {
  Chan* c = static_cast<Chan*>(chan);
  munmap(c->h, c->map_bytes);
  delete c;
}

}  // extern "C"
