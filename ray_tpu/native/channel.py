"""ctypes binding for the C++ shared-memory channel (channel.cc).

Reference: python/ray/experimental/channel/shared_memory_channel.py:159
over src/ray/core_worker/experimental_mutable_object_manager.h — the
compiled-DAG data plane: a pre-allocated mutable ring two processes on
one host exchange payloads through at memcpy speed, with blocking
acquire/release semantics (backpressure) instead of per-message object
allocation.

The .so builds lazily with g++ (no pybind11 in the image; the CPython
boundary is plain ctypes over an extern-C surface).
"""

from __future__ import annotations

import ctypes
import errno
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

_lib = None
_lib_lock = threading.Lock()


class ChannelClosed(ConnectionError):
    """The peer closed the channel (and, for readers, it is drained)."""


class ChannelPeerDied(ChannelClosed):
    """The peer PROCESS died without closing the channel (detected by
    the pid probe between blocked-wait slices).  Distinct from a clean
    close: recovery layers treat it as an actor/process death, not a
    drained stream."""


def _build_lib() -> str:
    src = os.path.join(os.path.dirname(__file__), "channel.cc")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.path.join(tempfile.gettempdir(),
                         f"ray_tpu_native_{os.getuid()}")
    os.makedirs(cache, exist_ok=True)
    out = os.path.join(cache, f"libray_tpu_channel_{digest}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".build{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src, "-lpthread"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise ImportError(
            f"building the native channel failed:\n{proc.stderr}")
    os.replace(tmp, out)  # atomic: racing builders converge
    return out


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_build_lib())
        lib.rtchan_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint64]
        lib.rtchan_create.restype = ctypes.c_int
        lib.rtchan_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.rtchan_open.restype = ctypes.c_void_p
        lib.rtchan_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64, ctypes.c_double]
        lib.rtchan_put.restype = ctypes.c_int
        lib.rtchan_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64, ctypes.c_double]
        lib.rtchan_get.restype = ctypes.c_int64
        lib.rtchan_next_len.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.rtchan_next_len.restype = ctypes.c_int64
        lib.rtchan_size.argtypes = [ctypes.c_void_p]
        lib.rtchan_size.restype = ctypes.c_int
        lib.rtchan_slot_bytes.argtypes = [ctypes.c_void_p]
        lib.rtchan_slot_bytes.restype = ctypes.c_int64
        lib.rtchan_n_slots.argtypes = [ctypes.c_void_p]
        lib.rtchan_n_slots.restype = ctypes.c_int64
        lib.rtchan_debug_lock.argtypes = [ctypes.c_void_p]
        lib.rtchan_debug_lock.restype = ctypes.c_int
        lib.rtchan_peer_dead.argtypes = [ctypes.c_void_p]
        lib.rtchan_peer_dead.restype = ctypes.c_int
        lib.rtchan_write_begin.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int64)]
        lib.rtchan_write_begin.restype = ctypes.c_void_p
        lib.rtchan_write_commit.argtypes = [ctypes.c_void_p,
                                            ctypes.c_uint64]
        lib.rtchan_write_commit.restype = ctypes.c_int
        lib.rtchan_read_begin.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int64)]
        lib.rtchan_read_begin.restype = ctypes.c_void_p
        lib.rtchan_read_commit.argtypes = [ctypes.c_void_p]
        lib.rtchan_read_commit.restype = ctypes.c_int
        lib.rtchan_close.argtypes = [ctypes.c_void_p]
        lib.rtchan_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class Channel:
    """Single-producer single-consumer mutable shm ring.

    ``Channel.create(...)`` allocates the backing file (once, by the
    coordinator); each side then constructs ``Channel(path,
    writer=...)``.  ``put``/``get`` move ``bytes`` payloads with
    blocking backpressure; ``close`` wakes both sides.
    """

    def __init__(self, path: str, *, writer: bool):
        lib = _load()
        self._lib = lib
        self._h = lib.rtchan_open(path.encode(), 1 if writer else 0)
        if not self._h:
            raise FileNotFoundError(
                f"no channel at {path!r} (create() first?)")
        self.path = path
        self.writer = writer

    # ------------------------------------------------------------ setup
    @staticmethod
    def create(path: Optional[str] = None, *, n_slots: int = 8,
               slot_bytes: int = 1 << 20) -> str:
        """Allocate the channel; returns its path (put it in /dev/shm
        so the ring lives in memory)."""
        lib = _load()
        if path is None:
            path = os.path.join(
                "/dev/shm" if os.path.isdir("/dev/shm")
                else tempfile.gettempdir(),
                f"rtchan-{os.getpid()}-{os.urandom(6).hex()}")
        rc = lib.rtchan_create(path.encode(), n_slots, slot_bytes)
        if rc != 0:
            raise OSError(-rc, os.strerror(-rc), path)
        return path

    # ------------------------------------------------------------- data
    def put(self, data: bytes, timeout: float = 60.0) -> None:
        rc = self._lib.rtchan_put(self._h, data, len(data),
                                  float(timeout))
        if rc == 0:
            return
        if rc == -errno.ECONNRESET:
            raise ChannelPeerDied(
                f"reader process of channel {self.path} died")
        if rc == -errno.EPIPE:
            raise ChannelClosed(f"channel {self.path} closed")
        if rc == -errno.ETIMEDOUT:
            raise TimeoutError(
                f"channel {self.path} full for {timeout}s")
        if rc == -errno.EMSGSIZE:
            raise ValueError(
                f"payload of {len(data)} bytes exceeds slot size")
        raise OSError(-rc, os.strerror(-rc))

    def get(self, timeout: float = 60.0) -> bytes:
        n = self._lib.rtchan_next_len(self._h, float(timeout))
        if n < 0:
            if n == -errno.ECONNRESET:
                raise ChannelPeerDied(
                    f"writer process of channel {self.path} died")
            if n == -errno.EPIPE:
                raise ChannelClosed(
                    f"channel {self.path} closed and drained")
            if n in (-errno.ETIMEDOUT, -errno.EAGAIN):
                raise TimeoutError(
                    f"channel {self.path} empty for {timeout}s")
            raise OSError(-n, os.strerror(-n))
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.rtchan_get(self._h, buf, int(n), float(timeout))
        if got < 0:
            raise OSError(-got, os.strerror(-got))
        return buf.raw[:got]

    # ------------------------------------------------ in-place access
    # SPSC makes direct slot access safe: the writer owns an
    # unpublished slot exclusively; the reader owns the head slot until
    # commit.  One memcpy per side instead of three (assemble / copy-in
    # / copy-out) — the channel data plane's hot path.

    def put_parts(self, parts, timeout: float = 60.0) -> None:
        """Assemble ``parts`` (bytes-like pieces) directly in the next
        free slot and publish; semantically ``put(b"".join(parts))``
        without the join copy."""
        srcs = []
        for p in parts:
            mv = p if isinstance(p, memoryview) else memoryview(p)
            srcs.append(mv if mv.format == "B" and mv.ndim == 1
                        else mv.cast("B"))
        total = sum(len(s) for s in srcs)
        if total > self.slot_bytes:
            self._raise_put_err(-errno.EMSGSIZE, total)
        err = ctypes.c_int64(0)
        base = self._lib.rtchan_write_begin(self._h, float(timeout),
                                            ctypes.byref(err))
        if not base:
            self._raise_put_err(int(err.value), total)
        view = memoryview(
            (ctypes.c_char * total).from_address(base)).cast("B")
        off = 0
        for s in srcs:
            view[off:off + len(s)] = s
            off += len(s)
        rc = self._lib.rtchan_write_commit(self._h, total)
        if rc != 0:
            self._raise_put_err(rc, total)

    def _raise_put_err(self, rc: int, length: int):
        if rc == -errno.ECONNRESET:
            raise ChannelPeerDied(
                f"reader process of channel {self.path} died")
        if rc == -errno.EPIPE:
            raise ChannelClosed(f"channel {self.path} closed")
        if rc == -errno.ETIMEDOUT:
            raise TimeoutError(f"channel {self.path} full")
        if rc == -errno.EMSGSIZE:
            raise ValueError(
                f"payload of {length} bytes exceeds slot size "
                f"{self.slot_bytes} of channel ring {self.path}")
        raise OSError(-rc, os.strerror(-rc))

    def get_buffer(self, timeout: float = 60.0) -> bytearray:
        """Receive the next frame as a fresh ``bytearray`` copied
        straight out of the slot (no zero-filled staging buffer, no
        second slice copy — the consumer may hold views into it)."""
        n = ctypes.c_int64(0)
        base = self._lib.rtchan_read_begin(self._h, float(timeout),
                                           ctypes.byref(n))
        if not base:
            v = int(n.value)
            if v == -errno.ECONNRESET:
                raise ChannelPeerDied(
                    f"writer process of channel {self.path} died")
            if v == -errno.EPIPE:
                raise ChannelClosed(
                    f"channel {self.path} closed and drained")
            if v in (-errno.ETIMEDOUT, -errno.EAGAIN):
                raise TimeoutError(
                    f"channel {self.path} empty for {timeout}s")
            raise OSError(-v, os.strerror(-v))
        ln = int(n.value)
        buf = bytearray((ctypes.c_char * ln).from_address(base))
        self._lib.rtchan_read_commit(self._h)
        return buf

    def qsize(self) -> int:
        return max(0, self._lib.rtchan_size(self._h))

    @property
    def slot_bytes(self) -> int:
        """Per-slot capacity; a payload above this cannot ride the ring
        (the adapter layer falls back to the object plane per-pass)."""
        return int(self._lib.rtchan_slot_bytes(self._h))

    @property
    def n_slots(self) -> int:
        return int(self._lib.rtchan_n_slots(self._h))

    def peer_dead(self) -> bool:
        """True when the OTHER endpoint's process attached and has since
        died (same pid probe the blocked waits run between slices)."""
        return bool(self._lib.rtchan_peer_dead(self._h))

    def _debug_lock(self) -> None:
        """Test hook: take the shared robust mutex and never release it
        (simulates a peer dying mid-critical-section)."""
        self._lib.rtchan_debug_lock(self._h)

    # --------------------------------------------------------- lifecycle
    def close(self) -> None:
        if self._h:
            self._lib.rtchan_close(self._h)

    def destroy(self) -> None:
        """Close, unmap, and unlink the backing file."""
        if self._h:
            self._lib.rtchan_close(self._h)
            self._lib.rtchan_free(self._h)
            self._h = None
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __del__(self):
        if getattr(self, "_h", None):
            try:
                self._lib.rtchan_free(self._h)
            except Exception:
                pass
            self._h = None
