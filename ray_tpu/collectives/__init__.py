"""``ray_tpu.collectives`` — cross-host array collectives over the DCN.

The ICI half of the collective story is XLA's (psum/all_gather inside
one jax runtime, see ``ray_tpu.parallel``); this package is the DCN
half: ring ``allreduce`` / ``allgather`` / ``broadcast`` between
processes/hosts that do NOT share a jax runtime, running over striped
raw sockets with chunked, reduce-overlapped transfers (docs/
networking.md).  ``train/`` gradient sync across worker groups and
``util/broadcast`` weight distribution build on this.

Usage (every member, same order — the SPMD contract)::

    from ray_tpu import collectives

    group = collectives.create_group("grad-sync", rank=r, world_size=n)
    grads = group.allreduce(grads, op="sum")       # numpy or jax.Array
    state = group.allreduce_tree(state, op="sum")  # one pass per dtype
    group.close()

Failure model: a dead or wedged peer raises a typed
:class:`~ray_tpu.exceptions.ChannelError` within the op deadline
(never a hang); ops also honor the ambient request deadline
(``core/deadlines.py``) and the chaos plane's ``collective_*`` hook
targets (``experimental/chaos.py``).
"""

from .group import (CollectiveGroup, allgather, allreduce, broadcast,
                    create_group, destroy_group, get_group)

__all__ = [
    "CollectiveGroup", "create_group", "destroy_group", "get_group",
    "allreduce", "allgather", "broadcast",
]
