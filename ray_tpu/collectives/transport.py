"""Collective transport: peer endpoints, rendezvous, framed sends.

Each group member runs one :class:`PeerServer` (a raw TCP listener,
sibling of the object plane's ObjectStreamServer) and dials its ring
neighbours directly — collective traffic never touches the framed RPC
plane or the head.  Rendezvous publishes each member's endpoint under
``__collectives__/<group>/<rank>`` in the head KV store (cluster mode)
or a process-local registry (local mode, where "members" are actors
sharing one process), then polls until the full membership is visible.

Wire protocol per peer connection (persistent for the group's life):

  handshake -> [8-byte len][pickle ("__coll__", group, from_rank)]
  then raw  -> [8-byte length][payload bytes] frames in both directions

Sends go out via ``sendall``/``sendmsg`` from live memoryviews; reads
``recv_into`` preallocated staging buffers — both sides GIL-released,
same discipline as the object plane's raw stream path.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

_LEN8 = struct.Struct(">Q")
_KV_NS = "__collectives__"

# Local-mode rendezvous: {group: {rank: address}} shared by the
# process's actor threads.
_local_groups: Dict[str, Dict[int, str]] = {}
_local_cond = threading.Condition()


def _tune(sock: socket.socket) -> None:
    from ..cluster.rpc import _tune_socket

    _tune_socket(sock)


class PeerConnection:
    """One framed, bidirectional peer link."""

    __slots__ = ("sock", "peer_rank")

    def __init__(self, sock: socket.socket, peer_rank: int):
        self.sock = sock
        self.peer_rank = peer_rank

    def send_frame(self, *bufs) -> None:
        from ..cluster.rpc import sendmsg_all

        total = sum(len(b) for b in bufs)
        sendmsg_all(self.sock, [memoryview(_LEN8.pack(total)), *bufs])

    def recv_frame_into(self, view: memoryview) -> int:
        """Read one frame into ``view`` (must be large enough);
        returns the frame length."""
        from ..cluster.rpc import _recv_exact

        (n,) = _LEN8.unpack(_recv_exact(self.sock, 8))
        if n > len(view):
            raise ConnectionError(
                f"oversize collective frame ({n} > {len(view)})")
        got = 0
        while got < n:
            r = self.sock.recv_into(view[got:n], n - got)
            if r == 0:
                raise ConnectionError("peer closed mid-frame")
            got += r
        return n

    def settimeout(self, t: Optional[float]) -> None:
        self.sock.settimeout(t)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _advertised_host() -> str:
    """The host other group members should dial: this node's
    cluster-advertised address (the same interface the object plane's
    ObjectStreamServer binds), loopback only in local mode."""
    cl = _cluster()
    if cl is not None:
        try:
            return cl.address.rsplit(":", 1)[0]
        except (AttributeError, ValueError):
            pass
    return "127.0.0.1"


class PeerServer:
    """Accepts tagged peer connections for one group member."""

    def __init__(self, group: str, rank: int,
                 host: Optional[str] = None):
        self.group = group
        self.rank = rank
        if host is None:
            host = _advertised_host()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self.address = "%s:%d" % self._sock.getsockname()
        self._inbox: Dict[int, socket.socket] = {}
        self._cond = threading.Condition()
        self._stopped = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"coll-{group}-{rank}").start()

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            _tune(conn)
            threading.Thread(target=self._handshake, args=(conn,),
                             daemon=True).start()

    def _handshake(self, conn: socket.socket):
        from ..cluster.rpc import _recv_exact

        try:
            conn.settimeout(30.0)
            (n,) = _LEN8.unpack(_recv_exact(conn, 8))
            tag, group, from_rank = pickle.loads(
                bytes(_recv_exact(conn, n)))
            if tag != "__coll__" or group != self.group:
                raise ConnectionError(f"bad handshake {tag!r}/{group!r}")
            conn.settimeout(None)
            with self._cond:
                self._inbox[int(from_rank)] = conn
                self._cond.notify_all()
        except (ConnectionError, OSError, EOFError,
                pickle.UnpicklingError):
            try:
                conn.close()
            except OSError:
                pass

    def accept_peer(self, from_rank: int,
                    timeout: float) -> PeerConnection:
        deadline = time.monotonic() + timeout
        with self._cond:
            while from_rank not in self._inbox:
                left = deadline - time.monotonic()
                if left <= 0 or self._stopped.is_set():
                    raise TimeoutError(
                        f"group {self.group!r} rank {self.rank}: peer "
                        f"{from_rank} never connected")
                self._cond.wait(left)
            return PeerConnection(self._inbox.pop(from_rank), from_rank)

    def shutdown(self):
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._cond:
            for conn in self._inbox.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._inbox.clear()
            self._cond.notify_all()


def connect_peer(address: str, group: str, my_rank: int,
                 timeout: float) -> PeerConnection:
    """Dial a peer's PeerServer, retrying until it is up (members
    start in any order) or the deadline passes."""
    host, port = address.rsplit(":", 1)
    deadline = time.monotonic() + timeout
    last: Optional[BaseException] = None
    while time.monotonic() < deadline:
        try:
            sock = socket.create_connection(
                (host, int(port)),
                timeout=max(0.1, min(5.0, deadline - time.monotonic())))
            _tune(sock)
            hs = pickle.dumps(("__coll__", group, my_rank))
            sock.sendall(_LEN8.pack(len(hs)) + hs)
            return PeerConnection(sock, -1)
        except OSError as e:
            last = e
            time.sleep(0.05)
    raise ConnectionError(
        f"cannot reach collective peer {address} for group "
        f"{group!r}: {last}")


# ---------------------------------------------------------------------------
# Rendezvous
# ---------------------------------------------------------------------------

def _cluster() :
    try:
        from ..core.runtime import get_runtime

        rt = get_runtime()
        return rt.cluster
    except Exception:
        return None


def publish_endpoint(group: str, rank: int, address: str) -> None:
    cl = _cluster()
    if cl is not None:
        cl.kv_put(f"{group}/{rank}", address, ns=_KV_NS)
        return
    with _local_cond:
        _local_groups.setdefault(group, {})[rank] = address
        _local_cond.notify_all()


def resolve_members(group: str, world_size: int,
                    timeout: float) -> List[str]:
    """Block until every rank's endpoint is published; returns
    addresses indexed by rank."""
    cl = _cluster()
    deadline = time.monotonic() + timeout
    if cl is None:
        with _local_cond:
            while len(_local_groups.get(group, {})) < world_size:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"collective rendezvous for {group!r} timed "
                        f"out at {len(_local_groups.get(group, {}))}"
                        f"/{world_size} members")
                _local_cond.wait(left)
            members = _local_groups[group]
            return [members[r] for r in range(world_size)]
    # Incremental scan: each endpoint is fetched from the head exactly
    # once (ranks publish before polling, so a seen key never changes
    # within one formation) — a tick costs one kv_get for the first
    # still-missing rank, not world_size of them.  Keeps head RPC load
    # linear in gang size instead of quadratic-at-20Hz.
    found: List[str] = []
    while True:
        while len(found) < world_size:
            v = cl.kv_get(f"{group}/{len(found)}", ns=_KV_NS)
            if v is None:
                break
            found.append(v)
        if len(found) == world_size:
            return found
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"collective rendezvous for {group!r} timed out at "
                f"{len(found)}/{world_size} members")
        time.sleep(0.05)


def retract_endpoint(group: str, rank: int) -> None:
    cl = _cluster()
    if cl is not None:
        try:
            cl.kv_del(f"{group}/{rank}", ns=_KV_NS)
        except Exception:
            pass  # head unreachable at teardown: keys expire unused
        return
    with _local_cond:
        members = _local_groups.get(group)
        if members is not None:
            members.pop(rank, None)
            if not members:
                _local_groups.pop(group, None)
