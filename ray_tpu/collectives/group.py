"""Ring collectives over the DCN: allreduce, allgather, broadcast.

Reference role: the object manager's push/pull plane moves *objects*;
gradient sync and weight distribution need *in-place array* collectives
at NIC line rate (SURVEY §N10/N11; SNIPPETS' pjit notes cover the ICI
half — this module is the DCN half, the layer ``train/`` gradient sync
and ``util/broadcast`` stand on when a gang spans hosts without a
shared jax runtime).

Algorithms (bandwidth-optimal ring, NCCL-style):

- ``allreduce``: ring reduce-scatter + ring allgather.  Each member
  moves ``2 * (n-1)/n * size`` bytes regardless of ``n``.  Segments
  move in adaptive chunks (cluster/geometry.py) and the receive side
  reduces each landed chunk while its send thread streams the next one
  out — reduce overlaps transfer, double-buffered staging, so the wire
  never idles behind the CPU adds.
- ``allgather``: ring pass-through, ``(n-1)/n * n * size`` moved.
- ``broadcast``: chunked pipeline around the ring — hop latency is one
  *chunk*, not one payload, so depth costs almost nothing.

Failure model: a dead or stalled peer surfaces as a typed
:class:`~ray_tpu.exceptions.ChannelError` naming the group, ranks, op
and round — never a hang.  Every op bounds itself by the group timeout
AND the ambient request deadline (core/deadlines.py), and chaos
schedules can sever deterministically via the ``collective_chunk``
RPC-hook target (experimental/chaos.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import deadlines
from ..exceptions import ChannelError
from .transport import (PeerServer, connect_peer, publish_endpoint,
                        resolve_members, retract_endpoint)

_REDUCE_OPS = {
    "sum": np.add,
    "prod": np.multiply,
    "min": np.minimum,
    "max": np.maximum,
}


def _is_jax_array(x) -> bool:
    import sys

    jax = sys.modules.get("jax")
    return jax is not None and isinstance(x, jax.Array)


class CollectiveGroup:
    """One member's handle on a named collective ring.

    Construction is a collective act: every member of ``world_size``
    must call it with the same ``name`` (rendezvous blocks until the
    ring closes).  Ops are synchronous and must be called by all
    members in the same order — the usual SPMD contract.
    """

    def __init__(self, name: str, rank: int, world_size: int, *,
                 timeout: float = 60.0):
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside [0, {world_size})")
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.timeout = timeout
        self._closed = False
        self._lock = threading.Lock()
        self._server = PeerServer(name, rank)
        publish_endpoint(name, rank, self._server.address)
        if world_size == 1:
            self._next = self._prev = None
            return
        try:
            members = resolve_members(name, world_size, timeout)
            # Dial next, accept prev — one persistent link each way
            # around the ring.
            self._next = connect_peer(members[(rank + 1) % world_size],
                                      name, rank, timeout)
            self._prev = self._server.accept_peer(
                (rank - 1) % world_size, timeout)
        except (ConnectionError, TimeoutError, OSError) as e:
            self._teardown()
            raise ChannelError(
                f"collective group setup failed: {e}",
                context={"group": name, "rank": rank,
                         "world_size": world_size}) from e

    # ------------------------------------------------------------ plumbing
    def _deadline(self, timeout: Optional[float]) -> float:
        """Monotonic deadline for one op: explicit timeout, else the
        group default, further clamped by the ambient request deadline
        (PR 5 plane) when one is installed."""
        budget = self.timeout if timeout is None else timeout
        ambient = deadlines.current()
        if ambient is not None:
            budget = min(budget, max(0.0, ambient - time.time()))
        return time.monotonic() + budget

    def _error(self, op: str, e: BaseException,
               **detail) -> ChannelError:
        if isinstance(e, ChannelError):
            return e
        kind = "stalled (deadline)" if isinstance(e, TimeoutError) \
            else "severed"
        return ChannelError(
            f"collective {op} {kind}: peer died or wedged mid-op "
            f"({e})",
            context={"group": self.name, "rank": self.rank,
                     "op": op, "cause": type(e).__name__, **detail})

    def _arm(self, deadline: float) -> None:
        left = deadline - time.monotonic()
        if left <= 0:
            raise TimeoutError(f"collective deadline expired")
        if self._next is not None:
            self._next.settimeout(left)
        if self._prev is not None:
            self._prev.settimeout(left)

    @staticmethod
    def _chunks(n: int) -> List[tuple]:
        from ..cluster.geometry import stripe_ranges, transfer_geometry

        chunk, _streams = transfer_geometry(n, what="collective",
                                            streams_cap=1)
        return stripe_ranges(n, chunk)

    @staticmethod
    def _chaos_chunk() -> None:
        from ..experimental import chaos

        chaos.on_rpc("collective_chunk")

    def _send_view(self, conn, view: memoryview,
                   err: List[Optional[BaseException]]) -> threading.Thread:
        """Stream ``view`` to ``conn`` chunk-framed from a background
        thread (ring sends and receives must run concurrently — a
        blocking send against a peer that is itself blocked sending
        would deadlock the ring once payloads outgrow socket buffers)."""
        def sender():
            try:
                for off, ln in self._chunks(len(view)):
                    self._chaos_chunk()
                    conn.send_frame(view[off:off + ln])
            except BaseException as e:  # noqa: BLE001
                err[0] = e

        t = threading.Thread(target=sender, daemon=True,
                             name=f"coll-send-{self.name}-{self.rank}")
        t.start()
        return t

    def _recv_into(self, conn, view: memoryview,
                   deadline: float) -> None:
        """Receive a chunk-framed stream into ``view`` (frame sizes
        mirror the sender's chunking).  Re-armed per frame: the socket
        timeout must track the SHRINKING remaining budget, or a
        trickling peer gets a full budget per frame (64 chunks x the
        deadline) instead of failing typed within it."""
        got = 0
        n = len(view)
        while got < n:
            self._arm(deadline)
            got += conn.recv_frame_into(view[got:])

    # ----------------------------------------------------------------- ops
    def allreduce(self, value, op: str = "sum", *,
                  timeout: Optional[float] = None):
        """Elementwise ``op`` reduction of ``value`` across all ranks;
        every rank returns the identical full result.  Accepts numpy or
        ``jax.Array`` (returned as the same kind; jax results are
        ``device_put`` with the input's sharding when reconstructable)."""
        if op not in _REDUCE_OPS:
            raise ValueError(f"unknown reduce op {op!r} "
                             f"(have {sorted(_REDUCE_OPS)})")
        return self._run("allreduce", self._allreduce_host, value,
                         timeout, op=op)

    def allgather(self, value, *, timeout: Optional[float] = None):
        """Stack every rank's ``value`` along a new leading axis
        (result shape ``(world_size, *value.shape)``, identical on all
        ranks)."""
        return self._run("allgather", self._allgather_host, value,
                         timeout, stacked=True)

    def broadcast(self, value, root: int = 0, *,
                  timeout: Optional[float] = None):
        """Every rank returns root's ``value`` (non-root inputs supply
        only shape/dtype)."""
        return self._run("broadcast", self._broadcast_host, value,
                         timeout, root=root)

    def _run(self, opname: str, fn, value, timeout, stacked=False,
             **kw):
        from ..cluster.serialization import _export_host
        from ..experimental import chaos

        if self._closed:
            raise ChannelError(
                f"collective group {self.name!r} is closed",
                context={"group": self.name, "rank": self.rank})
        was_jax = _is_jax_array(value)
        host = _export_host(value) if not isinstance(value, np.ndarray) \
            else np.ascontiguousarray(value)
        deadline = self._deadline(timeout)
        if deadline <= time.monotonic():
            # Shed, don't sever: no byte has moved, the ring is still
            # consistent — an inherited already-expired request budget
            # (PR 5 plane) must not cost the gang its group.
            from ..exceptions import DeadlineExceededError

            raise DeadlineExceededError(
                f"collective {opname} shed: deadline expired before "
                f"the op started (group={self.name!r} "
                f"rank={self.rank})")
        try:
            chaos.on_rpc(f"collective_{opname}")
            with self._lock:  # one op at a time per member (SPMD order)
                if self.world_size == 1:
                    out = np.stack([host]) if stacked else host.copy()
                else:
                    self._arm(deadline)
                    out = fn(host, deadline, **kw)
        except (ConnectionError, TimeoutError, OSError) as e:
            # close() outside the lock: teardown retracts the KV
            # endpoint over a head RPC, which must not stall a
            # concurrent op thread blocked on the lock.
            self.close()
            raise self._error(opname, e) from e
        if was_jax:
            from ..cluster.serialization import (_device_put_host,
                                                 _sharding_desc)

            return _device_put_host(
                out, None if stacked else _sharding_desc(value))
        return out

    # ring reduce-scatter + allgather
    def _allreduce_host(self, host: np.ndarray, deadline: float, *,
                        op: str) -> np.ndarray:
        n = self.world_size
        ufunc = _REDUCE_OPS[op]
        acc = host.copy()
        flat = acc.reshape(-1)
        # ml_dtypes (bfloat16, float8) accumulate exactly like jax
        # would on-chip; numpy ufuncs dispatch through ml_dtypes.
        bounds = np.linspace(0, flat.size, n + 1).astype(np.int64)
        segs = [(int(bounds[i]), int(bounds[i + 1])) for i in range(n)]
        longest = max(b - a for a, b in segs)
        # Double-buffered staging: recv chunk k+1 lands while chunk k
        # reduces (the send thread keeps the outbound side streaming
        # concurrently).
        staging = np.empty(longest, dtype=flat.dtype)
        sview = memoryview(staging.view(np.uint8))
        item = flat.dtype.itemsize
        err: List[Optional[BaseException]] = [None]

        for step in range(n - 1):
            self._arm(deadline)
            s_out = segs[(self.rank - step) % n]
            s_in = segs[(self.rank - step - 1) % n]
            out_v = memoryview(
                flat[s_out[0]:s_out[1]].view(np.uint8))
            t = self._send_view(self._next, out_v, err)
            in_len = s_in[1] - s_in[0]
            got = 0
            while got < in_len:
                self._arm(deadline)  # per-frame: budget shrinks
                self._chaos_chunk()
                nb = self._prev.recv_frame_into(
                    sview[got * item:in_len * item])
                nrecv = nb // item
                # Reduce the landed chunk immediately — the next frame
                # is already in flight behind it.
                ufunc(flat[s_in[0] + got:s_in[0] + got + nrecv],
                      staging[got:got + nrecv],
                      out=flat[s_in[0] + got:s_in[0] + got + nrecv])
                got += nrecv
            t.join(timeout=max(0.1, deadline - time.monotonic()))
            if err[0] is not None:
                raise err[0]
            if t.is_alive():
                raise TimeoutError("collective send stalled")
        # Allgather phase: circulate the now-complete segments.
        self._ring_allgather_segments(flat, segs, deadline,
                                      start=self.rank + 1)
        return acc

    def _ring_allgather_segments(self, flat: np.ndarray, segs,
                                 deadline: float, start: int) -> None:
        n = self.world_size
        err: List[Optional[BaseException]] = [None]
        for step in range(n - 1):
            self._arm(deadline)
            s_out = segs[(start - step) % n]
            s_in = segs[(start - step - 1) % n]
            out_v = memoryview(flat[s_out[0]:s_out[1]].view(np.uint8))
            in_v = memoryview(flat[s_in[0]:s_in[1]].view(np.uint8))
            t = self._send_view(self._next, out_v, err)
            self._recv_into(self._prev, in_v, deadline)
            t.join(timeout=max(0.1, deadline - time.monotonic()))
            if err[0] is not None:
                raise err[0]
            if t.is_alive():
                raise TimeoutError("collective send stalled")

    def _allgather_host(self, host: np.ndarray,
                        deadline: float) -> np.ndarray:
        n = self.world_size
        out = np.empty((n,) + host.shape, dtype=host.dtype)
        out[self.rank] = host
        flat = out.reshape(n, -1)
        seg = flat.shape[1]
        segs = [(r * seg, (r + 1) * seg) for r in range(n)]
        self._ring_allgather_segments(flat.reshape(-1), segs, deadline,
                                      start=self.rank)
        return out

    def _broadcast_host(self, host: np.ndarray, deadline: float, *,
                        root: int) -> np.ndarray:
        n = self.world_size
        out = host if self.rank == root else np.empty_like(host)
        view = memoryview(out.reshape(-1).view(np.uint8))
        is_root = self.rank == root
        next_is_root = (self.rank + 1) % n == root
        err: List[Optional[BaseException]] = [None]
        self._arm(deadline)
        if is_root:
            t = self._send_view(self._next, view, err)
            t.join(timeout=max(0.1, deadline - time.monotonic()))
            if err[0] is not None:
                raise err[0]
            if t.is_alive():
                raise TimeoutError("collective send stalled")
            return out
        # Pipeline hop: forward each landed chunk before reading the
        # next — ring depth costs one chunk of latency, not one
        # payload.
        got = 0
        total = len(view)
        while got < total:
            self._arm(deadline)  # per-frame: budget shrinks
            self._chaos_chunk()
            nb = self._prev.recv_frame_into(view[got:])
            if not next_is_root:
                self._next.send_frame(view[got:got + nb])
            got += nb
        return out

    # ------------------------------------------------------------- pytree
    def allreduce_tree(self, tree, op: str = "sum", *,
                       timeout: Optional[float] = None):
        """Allreduce every array leaf of a pytree in ONE ring pass:
        leaves pack into a single contiguous buffer (per dtype), so a
        million tiny gradient tensors cost one collective, not a
        million."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            return tree
        from ..cluster.serialization import _export_host

        hosts = [_export_host(x) if not isinstance(x, np.ndarray)
                 else np.ascontiguousarray(x) for x in leaves]
        was_jax = [_is_jax_array(x) for x in leaves]
        by_dtype: Dict[Any, List[int]] = {}
        for i, h in enumerate(hosts):
            by_dtype.setdefault(h.dtype, []).append(i)
        out_hosts: List[Optional[np.ndarray]] = [None] * len(hosts)
        for dtype, idxs in by_dtype.items():
            packed = np.concatenate(
                [hosts[i].reshape(-1) for i in idxs]) if len(idxs) > 1 \
                else hosts[idxs[0]].reshape(-1)
            reduced = self.allreduce(packed, op, timeout=timeout)
            off = 0
            for i in idxs:
                size = hosts[i].size
                out_hosts[i] = np.asarray(
                    reduced[off:off + size]).reshape(hosts[i].shape)
                off += size
        from ..cluster.serialization import (_device_put_host,
                                             _sharding_desc)

        outs = []
        for i, h in enumerate(out_hosts):
            if was_jax[i]:
                # Reapply the input leaf's sharding (same contract as
                # allreduce): gradients must land where the optimizer
                # step expects them, not all on device 0.
                outs.append(_device_put_host(
                    h, _sharding_desc(leaves[i])))
            else:
                outs.append(h)
        return jax.tree_util.tree_unflatten(treedef, outs)

    # ------------------------------------------------------------ teardown
    def _teardown(self) -> None:
        retract_endpoint(self.name, self.rank)
        for conn in (getattr(self, "_next", None),
                     getattr(self, "_prev", None)):
            if conn is not None:
                conn.close()
        self._server.shutdown()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._teardown()

    def __enter__(self) -> "CollectiveGroup":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Named-group registry (module-level convenience API)
# ---------------------------------------------------------------------------

_groups: Dict[str, CollectiveGroup] = {}
_groups_lock = threading.Lock()


def create_group(name: str, rank: int, world_size: int, *,
                 timeout: float = 60.0) -> CollectiveGroup:
    """Create (and register) this process/actor's membership in a
    named group.  All ``world_size`` members must call this."""
    # Close any old same-named group BEFORE constructing the new one:
    # close() retracts the rendezvous endpoint key, which would delete
    # the key the new group just published and strand other members
    # still polling resolve_members.
    with _groups_lock:
        old = _groups.pop(name, None)
    if old is not None:
        old.close()
    g = CollectiveGroup(name, rank, world_size, timeout=timeout)
    with _groups_lock:
        _groups[name] = g
    return g


def get_group(name: str) -> Optional[CollectiveGroup]:
    with _groups_lock:
        return _groups.get(name)


def destroy_group(name: str) -> None:
    with _groups_lock:
        g = _groups.pop(name, None)
    if g is not None:
        g.close()


def allreduce(value, op: str = "sum", *, group: str = "default",
              timeout: Optional[float] = None):
    return _require(group).allreduce(value, op, timeout=timeout)


def allgather(value, *, group: str = "default",
              timeout: Optional[float] = None):
    return _require(group).allgather(value, timeout=timeout)


def broadcast(value, root: int = 0, *, group: str = "default",
              timeout: Optional[float] = None):
    return _require(group).broadcast(value, root, timeout=timeout)


def _require(name: str) -> CollectiveGroup:
    g = get_group(name)
    if g is None:
        raise ValueError(
            f"no collective group {name!r} in this process — call "
            f"ray_tpu.collectives.create_group(...) on every member "
            f"first")
    return g
