"""Client proxy server: hosts remote thin drivers.

Reference: python/ray/util/client/server/proxier.py + server.py — a
gRPC service through which a laptop-side "Ray client" drives a cluster
it cannot join directly (NAT, firewalls, no fat runtime locally).  The
proxy executes put/get/task/actor operations against its own runtime
on the clients' behalf and hands back opaque reference tokens.

This build reuses the cluster RPC framing (array-aware two-pickle) —
one listening port, sessions scoped by a client-chosen id; dropping a
session releases every reference it holds.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.cluster.rpc import RpcServer
from ray_tpu.cluster.serialization import dumps, loads


def _sealed(handler):
    """Payloads cross as serialization bundles (cloudpickle + extern
    arrays — lambdas, local classes, and jax/bf16 arrays all work),
    riding the RPC layer's raw-bytes framing like task bundles do."""
    def wrapped(wire):
        return dumps(handler(loads(wire)))

    return wrapped


class ClientProxyServer:
    """Serves thin clients against this process's runtime (the driver
    or a head-host sidecar)."""

    # A session with no calls (incl. the client's keepalive ping,
    # every ~30s) for this long is presumed dead and its refs/actors
    # are released — the proxier's channel-drop cleanup, lease-style.
    SESSION_TTL_S = 120.0
    # Reaper tick.  A class attribute (not a literal in the loop) so
    # tests shrinking SESSION_TTL_S can shrink the tick with it —
    # otherwise a 0.5s-TTL test still waits out a full 10s tick.
    REAP_INTERVAL_S = 10.0

    def __init__(self, host: str = "127.0.0.1", port: int = 10001):
        self._lock = threading.Lock()
        # session_id -> {token: ObjectRef}
        self._refs: Dict[str, Dict[str, Any]] = {}
        # session_id -> {token: ActorHandle}
        self._actors: Dict[str, Dict[str, Any]] = {}
        self._last_seen: Dict[str, float] = {}
        self._stopped = threading.Event()
        self._server = RpcServer({
            "client_connect": _sealed(self._connect),
            "client_disconnect": _sealed(self._disconnect),
            "client_ping": _sealed(self._ping),
            "client_put": _sealed(self._put),  # raylint: disable=handler-idempotency -- thin clients call single-shot (no retry wrapper); a duplicate put would only mint an extra token
            "client_get": _sealed(self._get),
            "client_wait": _sealed(self._wait),
            "client_task": _sealed(self._task),
            "client_create_actor": _sealed(self._create_actor),
            "client_actor_call": _sealed(self._actor_call),
            "client_kill": _sealed(self._kill),
            "client_release": _sealed(self._release),
        }, host=host, port=port)
        self.address = self._server.address
        threading.Thread(target=self._reap_loop, daemon=True,
                         name="client-proxy-reaper").start()

    # ------------------------------------------------------------ session
    def _connect(self, p):
        sid = uuid.uuid4().hex
        with self._lock:
            self._refs[sid] = {}
            self._actors[sid] = {}
            self._last_seen[sid] = time.monotonic()
        return {"session": sid}

    def _ping(self, p):
        with self._lock:
            ok = p["session"] in self._refs
            if ok:
                self._last_seen[p["session"]] = time.monotonic()
        return {"ok": ok}

    def _reap_loop(self):
        while not self._stopped.wait(self.REAP_INTERVAL_S):
            cutoff = time.monotonic() - self.SESSION_TTL_S
            with self._lock:
                dead = [s for s, t in self._last_seen.items()
                        if t < cutoff]
            for sid in dead:
                self._disconnect({"session": sid})

    def _disconnect(self, p):
        with self._lock:
            refs = self._refs.pop(p["session"], {})
            actors = self._actors.pop(p["session"], {})
            self._last_seen.pop(p["session"], None)
        refs.clear()  # drops the proxy's holds; owner GC follows
        for handle in actors.values():
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
        return {"ok": True}

    def _table(self, p) -> Dict[str, Any]:
        # Caller must hold self._lock (or tolerate a raced disconnect
        # orphaning its insert — hence _hold/_lookup lock themselves).
        refs = self._refs.get(p["session"])
        if refs is None:
            raise ValueError(f"unknown client session {p['session']!r}")
        return refs

    def _touch_locked(self, p):
        self._last_seen[p["session"]] = time.monotonic()

    def _hold(self, p, ref) -> str:
        token = uuid.uuid4().hex
        with self._lock:
            self._table(p)[token] = ref
            self._touch_locked(p)
        return token

    def _lookup(self, p, tokens: List[str]) -> List[Any]:
        with self._lock:
            refs = self._table(p)
            self._touch_locked(p)
            return [refs[t] for t in tokens]

    def _resolve_args(self, p, args, kwargs):
        with self._lock:
            refs = dict(self._table(p))
            self._touch_locked(p)

        def conv(v):
            if isinstance(v, dict) and "__client_ref__" in v:
                return refs[v["__client_ref__"]]
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                out = [conv(x) for x in v]
                return type(v)(out) if isinstance(v, tuple) else out
            return v

        return tuple(conv(a) for a in args), \
            {k: conv(v) for k, v in kwargs.items()}

    # ------------------------------------------------------------- objects
    def _put(self, p):
        return {"ref": self._hold(p, ray_tpu.put(p["value"]))}

    def _get(self, p):
        targets = self._lookup(p, p["refs"])
        try:
            values = ray_tpu.get(targets, timeout=p.get("timeout"))
        except BaseException as e:  # noqa: BLE001
            return {"error": e}
        return {"values": values}

    def _wait(self, p):
        by_token = dict(zip(p["refs"], self._lookup(p, p["refs"])))
        ready, not_ready = ray_tpu.wait(
            list(by_token.values()),
            num_returns=p.get("num_returns", 1),
            timeout=p.get("timeout"))
        inv = {id(r): t for t, r in by_token.items()}
        return {"ready": [inv[id(r)] for r in ready],
                "not_ready": [inv[id(r)] for r in not_ready]}

    def _release(self, p):
        with self._lock:
            refs = self._table(p)
            for t in p["refs"]:
                refs.pop(t, None)
            self._touch_locked(p)
        return {"ok": True}

    # --------------------------------------------------------------- tasks
    def _task(self, p):
        args, kwargs = self._resolve_args(p, p["args"], p["kwargs"])
        fn = ray_tpu.remote(p["fn"])
        opts = p.get("options") or {}
        handle = fn.options(**opts) if opts else fn
        ref = handle.remote(*args, **kwargs)
        if isinstance(ref, (tuple, list)):  # num_returns > 1
            return {"refs": [self._hold(p, r) for r in ref]}
        return {"ref": self._hold(p, ref)}

    # -------------------------------------------------------------- actors
    def _create_actor(self, p):
        args, kwargs = self._resolve_args(p, p["args"], p["kwargs"])
        cls = ray_tpu.remote(p["cls"])
        opts = p.get("options") or {}
        handle = (cls.options(**opts) if opts else cls).remote(
            *args, **kwargs)
        token = uuid.uuid4().hex
        with self._lock:
            actors = self._actors.get(p["session"])
            if actors is not None:
                actors[token] = handle
                self._touch_locked(p)
        if actors is None:
            # Raced a disconnect: don't leak a running actor.  The
            # kill (a head RPC) runs after the proxy lock drops so
            # every other session isn't wedged behind it.
            ray_tpu.kill(handle)
            raise ValueError(
                f"client session {p['session']!r} is gone")
        return {"actor": token}

    def _actor_call(self, p):
        with self._lock:
            handle = self._actors[p["session"]][p["actor"]]
        args, kwargs = self._resolve_args(p, p["args"], p["kwargs"])
        ref = getattr(handle, p["method"]).remote(*args, **kwargs)
        return {"ref": self._hold(p, ref)}

    def _kill(self, p):
        with self._lock:
            handle = self._actors[p["session"]].pop(p["actor"], None)
        if handle is not None:
            ray_tpu.kill(handle)
        return {"ok": handle is not None}

    def shutdown(self):
        self._stopped.set()
        self._server.shutdown()
