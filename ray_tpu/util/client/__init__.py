"""Thin remote driver ("Ray client").

Reference: python/ray/util/client/worker.py:81 — a laptop-side client
that drives a cluster through one proxied connection instead of
joining it (`ray.init("ray://head:10001")`).  Here:

    from ray_tpu.util import client
    ctx = client.connect("head-host:10001")   # ClientProxyServer addr
    ref = ctx.put(big_array)
    double = ctx.remote(lambda x: x * 2)      # functions ship by value
    out = ctx.get(double.remote(ref))
    Counter = ctx.remote(CounterClass)
    c = Counter.remote()
    ctx.get(c.incr.remote())
    ctx.disconnect()                          # releases every held ref

Everything crosses ONE socket (array-aware serialization); references
are opaque tokens held by the proxy until released/disconnected.
"""

from __future__ import annotations

import inspect
import threading
from typing import Any, List, Optional

from ray_tpu.cluster.rpc import RpcClient
from ray_tpu.cluster.serialization import dumps, loads

from .server import ClientProxyServer  # noqa: F401  (re-export)


class ClientObjectRef:
    __slots__ = ("_ctx", "token")

    def __init__(self, ctx: "ClientContext", token: str):
        self._ctx = ctx
        self.token = token

    def _wire(self):
        return {"__client_ref__": self.token}

    def __repr__(self):
        return f"ClientObjectRef({self.token[:12]})"


def _wire_args(args, kwargs):
    """Refs → wire tokens, recursively through list/tuple/dict
    containers (a raw ClientObjectRef must never hit cloudpickle: it
    holds a socket)."""
    def conv(v):
        if isinstance(v, ClientObjectRef):
            return v._wire()
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, tuple):
            return tuple(conv(x) for x in v)
        if isinstance(v, list):
            return [conv(x) for x in v]
        return v

    return [conv(a) for a in args], {k: conv(v)
                                     for k, v in kwargs.items()}


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", fn, options=None):
        self._ctx = ctx
        self._fn = fn
        self._options = options or {}

    def options(self, **overrides) -> "ClientRemoteFunction":
        return ClientRemoteFunction(self._ctx, self._fn,
                                    {**self._options, **overrides})

    def remote(self, *args, **kwargs):
        wa, wk = _wire_args(args, kwargs)
        out = self._ctx._call("client_task", {
            "fn": self._fn, "args": wa, "kwargs": wk,
            "options": self._options})
        if "refs" in out:  # num_returns > 1
            return [ClientObjectRef(self._ctx, t)
                    for t in out["refs"]]
        return ClientObjectRef(self._ctx, out["ref"])


class ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        h = self._handle
        wa, wk = _wire_args(args, kwargs)
        out = h._ctx._call("client_actor_call", {
            "actor": h._token, "method": self._name,
            "args": wa, "kwargs": wk})
        return ClientObjectRef(h._ctx, out["ref"])


class ClientActorHandle:
    def __init__(self, ctx: "ClientContext", token: str):
        self._ctx = ctx
        self._token = token

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self, name)


class ClientActorClass:
    def __init__(self, ctx: "ClientContext", cls, options=None):
        self._ctx = ctx
        self._cls = cls
        self._options = options or {}

    def options(self, **overrides) -> "ClientActorClass":
        return ClientActorClass(self._ctx, self._cls,
                                {**self._options, **overrides})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        wa, wk = _wire_args(args, kwargs)
        out = self._ctx._call("client_create_actor", {
            "cls": self._cls, "args": wa, "kwargs": wk,
            "options": self._options})
        return ClientActorHandle(self._ctx, out["actor"])


class ClientContext:
    """One proxied driver session."""

    def __init__(self, address: str):
        self._rpc = RpcClient(address)
        self._session = loads(bytes(self._rpc.call(
            "client_connect", dumps({}))))["session"]
        self.address = address
        # Keepalive: the proxy reaps sessions silent past its TTL
        # (covers clients that die without disconnecting); a ping
        # every 30s keeps a blocked-in-get session alive.
        self._closed = threading.Event()
        threading.Thread(target=self._keepalive, daemon=True,
                         name=f"client-keepalive-{address}").start()

    def _keepalive(self):
        while not self._closed.wait(30.0):
            try:
                self._rpc.call("client_ping",
                               dumps({"session": self._session}),
                               timeout=30.0)
            except Exception:  # raylint: disable=ft-exception-swallow -- the keepalive loop must survive ANY ping failure (incl. server-shipped errors): if this thread dies, the proxy TTL-reaps the session out from under a live client
                pass

    def _call(self, method: str, payload: dict,
              timeout: Optional[float] = 600.0):
        payload["session"] = self._session
        out = loads(bytes(self._rpc.call(method, dumps(payload),
                                         timeout=timeout)))
        if isinstance(out, dict) and isinstance(
                out.get("error"), BaseException):
            raise out["error"]
        return out

    # ------------------------------------------------------------- API
    def put(self, value: Any) -> ClientObjectRef:
        out = self._call("client_put", {"value": value})
        return ClientObjectRef(self, out["ref"])

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        tokens = [refs.token] if single else [r.token for r in refs]
        # timeout=None blocks indefinitely, matching get() semantics
        # (the RPC wait blocks with it; the keepalive thread keeps the
        # session leased meanwhile).
        out = self._call("client_get", {"refs": tokens,
                                        "timeout": timeout},
                         timeout=None if timeout is None
                         else timeout + 30.0)
        values = out["values"]
        return values[0] if single else values

    def wait(self, refs: List[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None):
        out = self._call("client_wait", {
            "refs": [r.token for r in refs],
            "num_returns": num_returns, "timeout": timeout},
            timeout=None if timeout is None else timeout + 30.0)
        by_token = {r.token: r for r in refs}
        return ([by_token[t] for t in out["ready"]],
                [by_token[t] for t in out["not_ready"]])

    def remote(self, fn_or_class, **options):
        if inspect.isclass(fn_or_class):
            return ClientActorClass(self, fn_or_class, options)
        return ClientRemoteFunction(self, fn_or_class, options)

    def kill(self, handle: ClientActorHandle) -> None:
        self._call("client_kill", {"actor": handle._token})

    def release(self, refs: List[ClientObjectRef]) -> None:
        self._call("client_release",
                   {"refs": [r.token for r in refs]})

    def disconnect(self) -> None:
        self._closed.set()
        try:
            self._call("client_disconnect", {})
        finally:
            self._rpc.close()


def connect(address: str) -> ClientContext:
    """Connect a thin driver to a ClientProxyServer."""
    return ClientContext(address)
