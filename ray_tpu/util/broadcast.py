"""Object broadcast: proactively replicate one object to many nodes.

Reference: src/ray/object_manager/push_manager.h:30 — push-based
distribution instead of N pulls hammering one holder; the reference's
release envelope includes 1 GiB broadcast to 50+ nodes
(release/benchmarks/README.md:15-19).  The transport is a fanout tree
(cluster/client.py broadcast_object): the source uploads ``fanout``
copies, recipients relay to their subtrees.  Same-host recipients mmap
the source's /dev/shm flat layout (no bytes move); everyone else gets
a PIPELINED CHUNK STREAM (push_stream_* RPCs) whose chunks forward to
the next hop as they arrive — a depth-d relay tree streams at ~line
rate instead of d serial whole-payload store-and-forwards.

Typical use: ship a big read-only array (tokenizer table, eval set,
model shard) to every node before a task wave, so the wave's
dependency resolution hits local copies instead of serializing pulls.
"""

from __future__ import annotations

from typing import List, Optional


def broadcast(ref, node_ids: Optional[List[str]] = None,
              timeout: float = 600.0) -> int:
    """Replicate ``ref``'s value onto other nodes' object stores.

    ``node_ids``: target node ids (default: every other alive node).
    Returns the number of nodes that received a copy.  Copies are
    registered as borrowers with the owner, so the object stays alive
    until they go out of scope.  No-op (returns 0) in local mode.
    """
    from ..core.runtime import get_runtime

    rt = get_runtime()
    if rt.cluster is None:
        return 0
    addresses = None
    if node_ids is not None:
        by_id = {n["node_id"]: n for n in rt.cluster.list_nodes()}
        addresses = [by_id[i]["address"] for i in node_ids if i in by_id]
    return rt.cluster.broadcast_object(ref, addresses, timeout=timeout)
