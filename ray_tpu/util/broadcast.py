"""Object broadcast: proactively replicate one object to many nodes.

Reference: src/ray/object_manager/push_manager.h:30 — push-based
distribution instead of N pulls hammering one holder; the reference's
release envelope includes 1 GiB broadcast to 50+ nodes
(release/benchmarks/README.md:15-19).  The transport is a fanout tree
(cluster/client.py broadcast_object): the source uploads ``fanout``
copies, recipients relay to their subtrees.  Same-host recipients mmap
the source's /dev/shm flat layout (no bytes move); everyone else gets
a STRIPED, PIPELINED CHUNK STREAM (push_stream_* control RPCs + raw
push sockets, docs/networking.md) whose chunks forward to the next hop
as they arrive — a depth-d relay tree streams at ~line rate instead of
d serial whole-payload store-and-forwards.

Device arrays ride the same path natively: ``jax.Array`` leaves export
zero-copy (dlpack) into the wire layout with a header-only metadata
frame (dtype incl. bfloat16, shape, sharding), and each recipient
rebuilds with ``device_put`` straight from its staging buffer — so
weight distribution (model shards, optimizer state) costs one
device→host transfer at the source and one host→device per recipient,
with no pickle round-trip of the bytes in between.

Typical use: ship a big read-only array (tokenizer table, eval set,
model shard) to every node before a task wave, so the wave's
dependency resolution hits local copies instead of serializing pulls.
A severed or dead relay hop raises a typed
:class:`~ray_tpu.exceptions.ChannelError` naming the subtree — never a
hang (the stream read deadline bounds every hop).

For in-place array broadcast *within a collective gang* (every member
gets the value as an array, not an object ref), see
``ray_tpu.collectives.broadcast`` — it pipelines chunks around the
group ring instead of the cluster-wide fanout tree.
"""

from __future__ import annotations

from typing import List, Optional


def broadcast(ref, node_ids: Optional[List[str]] = None,
              timeout: float = 600.0) -> int:
    """Replicate ``ref``'s value onto other nodes' object stores.

    ``node_ids``: target node ids (default: every other alive node).
    Returns the number of nodes that received a copy.  Copies are
    CACHES (plasma foreign entries, no borrower holds at the owner):
    keep the ref alive through the task wave that uses it; idle copies
    are swept.  No-op (returns 0) in local mode.
    """
    from ..core.runtime import get_runtime

    rt = get_runtime()
    if rt.cluster is None:
        return 0
    addresses = None
    if node_ids is not None:
        by_id = {n["node_id"]: n for n in rt.cluster.list_nodes()}
        addresses = [by_id[i]["address"] for i in node_ids if i in by_id]
    return rt.cluster.broadcast_object(ref, addresses, timeout=timeout)


def broadcast_value(value, node_ids: Optional[List[str]] = None,
                    timeout: float = 600.0):
    """``put`` + :func:`broadcast` in one step: seal ``value`` (device
    arrays export zero-copy), replicate it cluster-wide, and return the
    ref for the task wave that consumes it.

    The weight-distribution idiom::

        ref = broadcast_value(params)          # one striped tree push
        ray_tpu.get([load.remote(ref, i) for i in range(n)])
    """
    import ray_tpu

    ref = ray_tpu.put(value)
    broadcast(ref, node_ids, timeout=timeout)
    return ref
