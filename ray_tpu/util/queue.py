"""Actor-backed distributed queue (reference: python/ray/util/queue.py:20)."""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self._queue: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        try:
            await asyncio.wait_for(self._queue.put(item), timeout)
        except asyncio.TimeoutError:
            raise Full("queue is full")

    async def get(self, timeout: Optional[float] = None):
        try:
            return await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            raise Empty("queue is empty")

    def put_nowait(self, item):
        try:
            self._queue.put_nowait(item)
        except asyncio.QueueFull:
            raise Full("queue is full")

    def get_nowait(self):
        try:
            return self._queue.get_nowait()
        except asyncio.QueueEmpty:
            raise Empty("queue is empty")

    def qsize(self) -> int:
        return self._queue.qsize()

    def empty(self) -> bool:
        return self._queue.empty()

    def full(self) -> bool:
        return self._queue.full()


class Queue:
    """FIFO queue usable from any task/actor; backed by an async actor."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        from .. import remote

        actor_options = actor_options or {}
        self.maxsize = maxsize
        self.actor = remote(_QueueActor).options(**actor_options).remote(
            maxsize)

    def __getstate__(self):
        return {"maxsize": self.maxsize, "actor": self.actor}

    def __setstate__(self, state):
        self.maxsize = state["maxsize"]
        self.actor = state["actor"]

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None):
        from .. import get

        if not block:
            get(self.actor.put_nowait.remote(item))
        else:
            get(self.actor.put.remote(item, timeout))

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        from .. import get as ray_get

        if not block:
            return ray_get(self.actor.get_nowait.remote())
        return ray_get(self.actor.get.remote(timeout))

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]):
        from .. import get

        refs = [self.actor.put_nowait.remote(i) for i in items]
        get(refs)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        from .. import get

        return [get(self.actor.get_nowait.remote())
                for _ in range(num_items)]

    def qsize(self) -> int:
        from .. import get

        return get(self.actor.qsize.remote())

    def empty(self) -> bool:
        from .. import get

        return get(self.actor.empty.remote())

    def full(self) -> bool:
        from .. import get

        return get(self.actor.full.remote())

    def shutdown(self):
        from .. import kill

        kill(self.actor)
