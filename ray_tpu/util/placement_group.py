"""Placement groups: gang-reserve resource bundles.

Reference semantics: python/ray/util/placement_group.py + GCS two-phase
bundle scheduling (SURVEY.md A.13).  A PG reserves a list of bundles with
a strategy (PACK/SPREAD/STRICT_PACK/STRICT_SPREAD); reserved capacity is
exposed as synthetic per-group resources (``CPU_group_<pgid>``) that
tasks/actors consume via PlacementGroupSchedulingStrategy.

TPU note: STRICT_PACK on a TPU slice means "same ICI domain" — the mesh
builder (ray_tpu.parallel.mesh) consumes PG bundle topology labels to lay
meshes along the torus.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..cluster.rpc import TRANSPORT_ERRORS
from ..core.ids import PlacementGroupID
from ..core.runtime import get_runtime
from ..core.task_spec import PlacementGroupSchedulingStrategy  # re-export

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD",
                    # ICI-topology-aware (core/tpu_topology.py labels):
                    # one gang on one slice / one pipeline stage per
                    # slice.  head._place_pg_by_slice.
                    "SLICE_PACK", "SLICE_SPREAD")

_lock = threading.Lock()
_groups: Dict[PlacementGroupID, "PlacementGroup"] = {}


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self._ready_event = threading.Event()
        self._removed = False
        # Guards the ready/removed handoff: reservation completes in a
        # background thread, and exactly ONE of {reserver, remover} must
        # tear a completed reservation down.
        self._state_lock = threading.Lock()
        # Cluster mode: {"nodes": [node_id per bundle],
        # "addresses": [addr per bundle]} once reserved.
        self._cluster_assignment: Optional[Dict[str, List[str]]] = None

    # -- lifecycle -----------------------------------------------------------
    def ready(self):
        """Returns an ObjectRef resolving when all bundles are reserved
        (reference: PlacementGroup.ready())."""
        from .. import remote

        @remote
        def _pg_ready(pg_id_hex: str):
            pg = get_placement_group_by_id(
                PlacementGroupID.from_hex(pg_id_hex))
            pg.wait(timeout_seconds=None)
            return pg

        return _pg_ready.remote(self.id.hex())

    def wait(self, timeout_seconds: Optional[float] = 30) -> bool:
        return self._ready_event.wait(timeout_seconds)

    def is_ready(self) -> bool:
        return self._ready_event.is_set()

    # -- resource mapping ----------------------------------------------------
    def group_resource_name(self, base: str, bundle_index: int = -1) -> str:
        if bundle_index >= 0:
            return f"{base}_group_{bundle_index}_{self.id.hex()}"
        return f"{base}_group_{self.id.hex()}"

    def wrap_resources(self, demand: Dict[str, float],
                       bundle_index: int = -1) -> Dict[str, float]:
        """Rewrite a task's demand onto this PG's synthetic resources.

        Single-node mode mints capacity only at the aggregate
        (wildcard) level, so indexed and wildcard consumers draw from one
        pool — on one node every bundle is co-located anyway, and a split
        pool would let the two forms double-spend the reservation.
        Cluster mode mints per-bundle indexed capacity on the node
        holding each bundle (reference: CPU_group_<i>_<pgid> synthetic
        resources, raylet/placement_group_resource_manager.h), so an
        indexed demand lands exactly on its bundle's node.
        """
        if self._removed:
            raise ValueError(f"placement group {self.id!r} was removed")
        if bundle_index >= len(self.bundles):
            raise ValueError(
                f"bundle index {bundle_index} out of range "
                f"(PG has {len(self.bundles)} bundles)")
        if self._cluster_assignment is not None and bundle_index >= 0:
            # Demand BOTH the indexed and the wildcard name (reference:
            # indexed consumers debit the wildcard pool too,
            # placement_group_resource_manager.h) — otherwise an
            # indexed and a wildcard consumer double-spend one bundle.
            out: Dict[str, float] = {}
            for k, v in demand.items():
                out[self.group_resource_name(k, bundle_index)] = v
                out[self.group_resource_name(k)] = v
            return out
        return {self.group_resource_name(k): v for k, v in demand.items()}

    def synthetic_capacity(self) -> Dict[str, float]:
        cap: Dict[str, float] = {}
        for bundle in self.bundles:
            for k, v in bundle.items():
                name = self.group_resource_name(k)
                cap[name] = cap.get(name, 0.0) + v
        return cap

    def bundle_specs(self) -> List[Dict[str, float]]:
        return [dict(b) for b in self.bundles]

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def __reduce__(self):
        return (get_placement_group_by_id, (self.id,))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    for b in bundles:
        if any(v < 0 for v in b.values()):
            raise ValueError("bundle resources must be non-negative")
    rt = get_runtime()
    pg = PlacementGroup(PlacementGroupID.from_random(), bundles, strategy,
                        name)
    with _lock:
        _groups[pg.id] = pg

    if rt.cluster is not None:
        threading.Thread(target=_reserve_cluster, args=(rt, pg),
                         daemon=True).start()
        return pg

    # Single node: acquire the aggregate demand locally, then mint
    # synthetic bundle resources (the one-node analogue of the GCS
    # two-phase prepare/commit across raylets).
    total: Dict[str, float] = {}
    for b in bundles:
        for k, v in b.items():
            total[k] = total.get(k, 0.0) + v

    def reserve():
        if not rt.node_resources.can_ever_fit(total):
            return  # infeasible — stays pending forever, like reference
        rt.node_resources.acquire(total)
        with pg._state_lock:
            if not pg._removed:
                rt.node_resources.add_capacity(pg.synthetic_capacity())
                pg._ready_event.set()
                return
        # Removed while we were acquiring: give the resources back.
        rt.node_resources.release(total)

    threading.Thread(target=reserve, daemon=True).start()
    return pg


def bundle_capacity(pg_id_hex: str,
                    bundles: Dict[int, Dict[str, float]]
                    ) -> Dict[str, float]:
    """Synthetic resources a node advertises for the PG bundles it
    hosts: indexed (``CPU_group_<i>_<pgid>``) + wildcard aggregate
    (``CPU_group_<pgid>``) — reference
    raylet/placement_group_resource_manager.h."""
    cap: Dict[str, float] = {}
    for i, bundle in bundles.items():
        for k, v in bundle.items():
            idx = f"{k}_group_{i}_{pg_id_hex}"
            wild = f"{k}_group_{pg_id_hex}"
            cap[idx] = cap.get(idx, 0.0) + v
            cap[wild] = cap.get(wild, 0.0) + v
    return cap


def _reserve_cluster(rt, pg: PlacementGroup) -> None:
    """Cluster reservation: the head assigns each bundle a node
    (strategy-aware, head._create_pg), then every chosen node mints the
    bundle's synthetic resources against its real capacity (the
    two-phase prepare/commit of SURVEY A.13, collapsed to assign+mint
    with per-node rollback on failure)."""
    resp = rt.cluster.mut_call("create_pg", {
        "pg_id": pg.id.hex(), "bundles": pg.bundles,
        "strategy": pg.strategy}, timeout=30.0)
    if not resp.get("ok"):
        return  # infeasible — stays pending, like the reference
    nodes, addrs = resp["nodes"], resp["addresses"]
    by_addr: Dict[str, Dict[int, Dict[str, float]]] = {}
    for i, addr in enumerate(addrs):
        by_addr.setdefault(addr, {})[i] = pg.bundles[i]
    minted: List[str] = []
    for addr, bundles in by_addr.items():
        try:
            r = rt.cluster.pool.get(addr).call(
                "add_pg_capacity",
                {"pg_id": pg.id.hex(), "bundles": bundles}, timeout=60.0)
        except Exception:  # raylint: disable=ft-exception-swallow -- any mint failure (transport or node-side) routes to the same rollback below
            r = {"ok": False}
        if not r.get("ok"):
            for done in minted:  # roll back nodes already minted
                try:
                    rt.cluster.pool.get(done).call(
                        "remove_pg_capacity",
                        {"pg_id": pg.id.hex(),
                         "bundles": by_addr[done]}, timeout=30.0)
                except TRANSPORT_ERRORS:
                    pass  # rollback target died: its capacity died too
            rt.cluster.mut_call("remove_pg", {"pg_id": pg.id.hex()})
            return
        minted.append(addr)
    pg._cluster_assignment = {"nodes": nodes, "addresses": addrs}
    with pg._state_lock:
        if not pg._removed:
            pg._ready_event.set()
            return
    # remove_placement_group ran while we were reserving (it saw
    # not-ready and tore nothing down): undo everything now.
    for addr, bundles in by_addr.items():
        try:
            rt.cluster.pool.get(addr).call(
                "remove_pg_capacity",
                {"pg_id": pg.id.hex(), "bundles": bundles},
                timeout=30.0)
        except TRANSPORT_ERRORS:
            pass  # node gone: nothing left to unmint
    try:
        rt.cluster.mut_call("remove_pg", {"pg_id": pg.id.hex()})
    except TRANSPORT_ERRORS:
        pass  # head unreachable: the PG table entry dies with it


def get_placement_group_by_id(pg_id: PlacementGroupID) -> PlacementGroup:
    with _lock:
        pg = _groups.get(pg_id)
    if pg is None:
        raise ValueError(f"no such placement group: {pg_id!r}")
    return pg


def remove_placement_group(pg: PlacementGroup):
    rt = get_runtime()
    with _lock:
        _groups.pop(pg.id, None)
    with pg._state_lock:
        was_ready = pg.is_ready()
        pg._removed = True
    if was_ready:
        if pg._cluster_assignment is not None:
            by_addr: Dict[str, Dict[int, Dict[str, float]]] = {}
            for i, addr in enumerate(pg._cluster_assignment["addresses"]):
                by_addr.setdefault(addr, {})[i] = pg.bundles[i]
            for addr, bundles in by_addr.items():
                try:
                    rt.cluster.pool.get(addr).call(
                        "remove_pg_capacity",
                        {"pg_id": pg.id.hex(), "bundles": bundles},
                        timeout=30.0)
                except TRANSPORT_ERRORS:
                    pass  # node gone: nothing left to unmint
            try:
                rt.cluster.mut_call("remove_pg", {"pg_id": pg.id.hex()})
            except TRANSPORT_ERRORS:
                pass  # head unreachable: the PG table entry dies with it
        else:
            rt.node_resources.remove_capacity(pg.synthetic_capacity())
            total: Dict[str, float] = {}
            for b in pg.bundles:
                for k, v in b.items():
                    total[k] = total.get(k, 0.0) + v
            rt.node_resources.release(total)


def get_current_placement_group() -> Optional[PlacementGroup]:
    # In-process runtime: tasks don't implicitly capture the parent's PG
    # unless placement_group_capture_child_tasks is set; we expose None
    # outside PG tasks. Cluster mode threads this through TaskContext.
    return None


def placement_group_table() -> List[Dict[str, Any]]:
    with _lock:
        return [
            {"id": pg.id.hex(), "name": pg.name, "strategy": pg.strategy,
             "bundles": pg.bundle_specs(), "ready": pg.is_ready()}
            for pg in _groups.values()
        ]
