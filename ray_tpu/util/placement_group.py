"""Placement groups: gang-reserve resource bundles.

Reference semantics: python/ray/util/placement_group.py + GCS two-phase
bundle scheduling (SURVEY.md A.13).  A PG reserves a list of bundles with
a strategy (PACK/SPREAD/STRICT_PACK/STRICT_SPREAD); reserved capacity is
exposed as synthetic per-group resources (``CPU_group_<pgid>``) that
tasks/actors consume via PlacementGroupSchedulingStrategy.

TPU note: STRICT_PACK on a TPU slice means "same ICI domain" — the mesh
builder (ray_tpu.parallel.mesh) consumes PG bundle topology labels to lay
meshes along the torus.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..core.ids import PlacementGroupID
from ..core.runtime import get_runtime
from ..core.task_spec import PlacementGroupSchedulingStrategy  # re-export

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")

_lock = threading.Lock()
_groups: Dict[PlacementGroupID, "PlacementGroup"] = {}


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self._ready_event = threading.Event()
        self._removed = False

    # -- lifecycle -----------------------------------------------------------
    def ready(self):
        """Returns an ObjectRef resolving when all bundles are reserved
        (reference: PlacementGroup.ready())."""
        from .. import remote

        @remote
        def _pg_ready(pg_id_hex: str):
            pg = get_placement_group_by_id(
                PlacementGroupID.from_hex(pg_id_hex))
            pg.wait(timeout_seconds=None)
            return pg

        return _pg_ready.remote(self.id.hex())

    def wait(self, timeout_seconds: Optional[float] = 30) -> bool:
        return self._ready_event.wait(timeout_seconds)

    def is_ready(self) -> bool:
        return self._ready_event.is_set()

    # -- resource mapping ----------------------------------------------------
    def group_resource_name(self, base: str, bundle_index: int = -1) -> str:
        if bundle_index >= 0:
            return f"{base}_group_{bundle_index}_{self.id.hex()}"
        return f"{base}_group_{self.id.hex()}"

    def wrap_resources(self, demand: Dict[str, float],
                       bundle_index: int = -1) -> Dict[str, float]:
        """Rewrite a task's demand onto this PG's synthetic resources.

        Single-node note: capacity is minted only at the aggregate
        (wildcard) level, so indexed and wildcard consumers draw from one
        pool — on one node every bundle is co-located anyway, and a split
        pool would let the two forms double-spend the reservation.
        Cluster mode places bundles on nodes and enforces per-bundle
        capacity there.
        """
        if self._removed:
            raise ValueError(f"placement group {self.id!r} was removed")
        if bundle_index >= len(self.bundles):
            raise ValueError(
                f"bundle index {bundle_index} out of range "
                f"(PG has {len(self.bundles)} bundles)")
        return {self.group_resource_name(k): v for k, v in demand.items()}

    def synthetic_capacity(self) -> Dict[str, float]:
        cap: Dict[str, float] = {}
        for bundle in self.bundles:
            for k, v in bundle.items():
                name = self.group_resource_name(k)
                cap[name] = cap.get(name, 0.0) + v
        return cap

    def bundle_specs(self) -> List[Dict[str, float]]:
        return [dict(b) for b in self.bundles]

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def __reduce__(self):
        return (get_placement_group_by_id, (self.id,))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    for b in bundles:
        if any(v < 0 for v in b.values()):
            raise ValueError("bundle resources must be non-negative")
    rt = get_runtime()
    pg = PlacementGroup(PlacementGroupID.from_random(), bundles, strategy,
                        name)
    with _lock:
        _groups[pg.id] = pg

    # Reserve: acquire the aggregate demand from the node, then mint
    # synthetic bundle resources (the one-node analogue of the GCS
    # two-phase prepare/commit across raylets).
    total: Dict[str, float] = {}
    for b in bundles:
        for k, v in b.items():
            total[k] = total.get(k, 0.0) + v

    def reserve():
        if not rt.node_resources.can_ever_fit(total):
            return  # infeasible — stays pending forever, like reference
        rt.node_resources.acquire(total)
        rt.node_resources.add_capacity(pg.synthetic_capacity())
        pg._ready_event.set()

    threading.Thread(target=reserve, daemon=True).start()
    return pg


def get_placement_group_by_id(pg_id: PlacementGroupID) -> PlacementGroup:
    with _lock:
        pg = _groups.get(pg_id)
    if pg is None:
        raise ValueError(f"no such placement group: {pg_id!r}")
    return pg


def remove_placement_group(pg: PlacementGroup):
    rt = get_runtime()
    with _lock:
        _groups.pop(pg.id, None)
    if pg.is_ready():
        rt.node_resources.remove_capacity(pg.synthetic_capacity())
        total: Dict[str, float] = {}
        for b in pg.bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        rt.node_resources.release(total)
    pg._removed = True


def get_current_placement_group() -> Optional[PlacementGroup]:
    # In-process runtime: tasks don't implicitly capture the parent's PG
    # unless placement_group_capture_child_tasks is set; we expose None
    # outside PG tasks. Cluster mode threads this through TaskContext.
    return None


def placement_group_table() -> List[Dict[str, Any]]:
    with _lock:
        return [
            {"id": pg.id.hex(), "name": pg.name, "strategy": pg.strategy,
             "bundles": pg.bundle_specs(), "ready": pg.is_ready()}
            for pg in _groups.values()
        ]
