"""Pool of actors for map-style workloads (reference:
python/ray/util/actor_pool.py)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, Tuple


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[Tuple[Callable, Any]] = []

    def submit(self, fn: Callable[[Any, Any], Any], value: Any):
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        from .. import get

        if not self.has_next():
            raise StopIteration("no pending results")
        idx = self._next_return_index
        self._next_return_index += 1
        future = self._index_to_future.pop(idx)
        result = get(future, timeout=timeout)
        self._return_actor(future)
        return result

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        from .. import get, wait

        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = wait(list(self._future_to_actor), num_returns=1,
                        timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        idx, _actor = self._future_to_actor[future]
        self._index_to_future.pop(idx, None)
        result = get(future)
        self._return_actor(future)
        return result

    def _return_actor(self, future):
        _idx, actor = self._future_to_actor.pop(future)
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None

    def push(self, actor: Any):
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)
