"""Pool of actors for map-style workloads.

API parity target: ``ray.util.ActorPool`` (submit / map /
map_unordered / get_next / get_next_unordered / has_next / has_free /
pop_idle / push).  Implementation is a sequence-numbered in-flight
table: every submitted call gets a monotonically increasing ticket;
ordered consumption walks tickets in order, unordered consumption
takes whatever ``wait`` surfaces first.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, Optional


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._free = deque(actors)
        # ticket -> (object ref, actor running it)
        self._inflight: dict = {}
        self._ref_ticket: dict = {}
        self._issue = 0    # next ticket to hand out
        self._serve = 0    # next ticket get_next() returns
        self._backlog: deque = deque()  # (fn, value) waiting for an actor

    # ------------------------------------------------------------ submit
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """Run ``fn(actor, value)`` on a free actor, or queue it."""
        if not self._free:
            self._backlog.append((fn, value))  # raylint: disable=unbounded-mailbox -- reference ActorPool semantics: the pool owner drives submission and map() gates on results, so the backlog is caller-paced
            return
        actor = self._free.popleft()
        ref = fn(actor, value)
        ticket = self._issue
        self._issue += 1
        self._inflight[ticket] = (ref, actor)
        self._ref_ticket[ref] = ticket

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ----------------------------------------------------------- results
    def has_next(self) -> bool:
        return bool(self._inflight) or bool(self._backlog)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in submission order."""
        from .. import get

        if not self.has_next():
            raise StopIteration("no pending results")
        ticket = self._serve
        self._serve += 1
        ref, actor = self._inflight.pop(ticket)
        self._ref_ticket.pop(ref, None)
        result = get(ref, timeout=timeout)
        self._recycle(actor)
        return result

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Whichever in-flight result completes first."""
        from .. import get, wait

        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = wait([ref for ref, _a in self._inflight.values()],
                        num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        ticket = self._ref_ticket.pop(ref)
        _ref, actor = self._inflight.pop(ticket)
        result = get(ref)
        self._recycle(actor)
        return result

    # ------------------------------------------------------------ actors
    def _recycle(self, actor: Any) -> None:
        """Actor finished a call: feed it the backlog or park it."""
        self._free.append(actor)
        if self._backlog:
            fn, value = self._backlog.popleft()
            self.submit(fn, value)

    def has_free(self) -> bool:
        return bool(self._free)

    def pop_idle(self) -> Optional[Any]:
        return self._free.pop() if self._free else None

    def push(self, actor: Any) -> None:
        self._recycle(actor)
