"""Cluster/runtime state listing.

Reference: python/ray/util/state/api.py:110,781,1008 — ``ray list
tasks/actors/objects/nodes`` aggregating GCS + workers; server side
dashboard/state_aggregator.py.  Here the local runtime answers for its
own tables and the head answers cluster-wide questions.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def _runtime():
    from ..core.runtime import get_runtime

    return get_runtime()


def list_tasks(*, include_done: bool = False) -> List[Dict[str, Any]]:
    """Pending (owner-side) tasks; with ``include_done``, also every
    finished task recorded in the timeline buffer this session."""
    rt = _runtime()
    out = []
    with rt.task_manager._lock:
        pending = list(rt.task_manager._pending.values())
    for spec in pending:
        out.append({
            "task_id": spec.task_id.hex(),
            "name": spec.repr_name(),
            "state": "PENDING",
            "kind": ("ACTOR_CREATION" if spec.is_actor_creation else
                     "ACTOR_TASK" if spec.is_actor_task else "TASK"),
            "attempt": spec.attempt_number,
            # In-flight tasks must be filterable by --trace-id too —
            # a currently-stuck pass is the query's whole point.
            "trace_id": spec.trace_id,
        })
    if include_done:
        from ..observability.timeline import export_timeline

        for ev in export_timeline():
            args = ev.get("args") or {}
            if "task_id" in args:
                out.append({
                    "task_id": args["task_id"],
                    "name": ev["name"],
                    "state": ("FINISHED" if args.get("outcome") == "ok"
                              else "FAILED"),
                    "kind": args.get("kind", "task").upper(),
                    "attempt": args.get("attempt", 0),
                    "duration_s": ev.get("dur", 0) / 1e6,
                    # Distributed-trace correlation (spans recorded on
                    # any node of the same pass share this id).
                    "trace_id": args.get("trace_id"),
                })
    return out


def list_actors() -> List[Dict[str, Any]]:
    """Local actors plus (in cluster mode) every actor the head knows."""
    rt = _runtime()
    out = []
    with rt.actor_manager._lock:
        cores = list(rt.actor_manager._cores.values())
    for core in cores:
        info = core.info
        out.append({
            "actor_id": info.actor_id.hex(),
            "class_name": info.klass.__name__,
            "name": info.name, "namespace": info.namespace,
            "state": info.state.name
            if hasattr(info.state, "name") else str(info.state),
            "node_id": rt.node_id.hex(),
            "pid": __import__("os").getpid(),
        })
    if rt.cluster is not None:
        local_ids = {a["actor_id"] for a in out}
        try:
            for a in rt.cluster.head.call("list_actors", None,
                                          timeout=10.0):
                aid = a["actor_id"].hex() if hasattr(
                    a["actor_id"], "hex") else str(a["actor_id"])
                if aid not in local_ids:
                    out.append({
                        "actor_id": aid,
                        "class_name": "",
                        "name": a.get("name", ""),
                        "namespace": "",
                        "state": a.get("state", "ALIVE"),
                        "node_id": a.get("node_id", ""),
                        "pid": None,
                    })
        except Exception:  # raylint: disable=ft-exception-swallow -- state listing degrades to the local view when the head (or its reply shape) is unavailable
            pass
    return out


def list_objects() -> List[Dict[str, Any]]:
    rt = _runtime()
    with rt.object_store._lock:
        items = list(rt.object_store._objects.items())
    out = []
    for oid, obj in items:
        out.append({
            "object_id": oid.hex(),
            "is_error": obj.is_error(),
            "size_bytes": obj.size_bytes,
        })
    return out


def list_nodes() -> List[Dict[str, Any]]:
    import ray_tpu

    return ray_tpu.nodes()


def summarize_tasks() -> Dict[str, int]:
    from ..observability import metrics as _metrics

    summary: Dict[str, int] = {"PENDING": len(list_tasks())}
    snap = _metrics.metrics_summary()
    for name, series in snap.items():
        if name == "ray_tpu_tasks_finished":
            summary["FINISHED"] = int(sum(series.values()))
        if name == "ray_tpu_tasks_failed":
            summary["FAILED"] = int(sum(series.values()))
    return summary
