from .actor_pool import ActorPool
from .broadcast import broadcast, broadcast_value
from .placement_group import (PlacementGroup, placement_group,
                              remove_placement_group,
                              get_current_placement_group)
from .queue import Queue

__all__ = [
    "ActorPool", "PlacementGroup", "broadcast", "broadcast_value",
    "placement_group",
    "remove_placement_group", "get_current_placement_group", "Queue",
]
