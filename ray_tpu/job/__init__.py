"""Job submission: run driver scripts as supervised subprocesses.

Reference: the dashboard job module — JobManager
(dashboard/modules/job/job_manager.py:59) starts a per-job
``JobSupervisor`` actor (job_supervisor.py:54) which runs the
entrypoint as a subprocess, tracks its status in the GCS job table,
and captures its logs; the SDK (modules/job/sdk.py:35) submits/polls/
stops.  Same shape here minus the REST layer: ``submit_job`` creates a
detached supervisor actor on the cluster, job metadata lives in the
head KV under the "jobs" namespace, and logs land in a per-job file
the supervisor can stream back.

Runtime env: ``working_dir`` (the subprocess cwd) and ``env_vars`` are
materialized; pip/conda envs are out of scope for this image (no
network installs) and raise.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_KV_NS = "jobs"

VALID_STATUSES = ("PENDING", "RUNNING", "SUCCEEDED", "FAILED", "STOPPED")


class JobSupervisor:
    """Detached actor owning one job subprocess
    (job_supervisor.py:54)."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[Dict[str, Any]] = None,
                 log_dir: Optional[str] = None):
        import ray_tpu

        self.job_id = job_id
        self.entrypoint = entrypoint
        runtime_env = runtime_env or {}
        unsupported = set(runtime_env) - {"working_dir", "env_vars"}
        if unsupported:
            raise ValueError(
                f"runtime_env keys {sorted(unsupported)} are not "
                f"supported (no network installs in this environment)")
        self._rt = ray_tpu.get_runtime()
        log_dir = log_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "ray_tpu_jobs")
        os.makedirs(log_dir, exist_ok=True)
        self.log_path = os.path.join(log_dir, f"{job_id}.log")
        env = dict(os.environ)
        env.update({str(k): str(v)
                    for k, v in (runtime_env.get("env_vars") or {}).items()})
        head = getattr(self._rt.cluster, "head_address", "")
        if head:
            env["RAY_TPU_HEAD_ADDRESS"] = head
        cwd = runtime_env.get("working_dir") or None
        self._update(status="RUNNING", start_time=time.time())
        self._log = open(self.log_path, "wb")  # raylint: disable=resource-teardown -- the waiter thread closes the log when the child exits (stop() terminates the child, which unblocks the waiter)
        self._proc = subprocess.Popen(
            entrypoint, shell=True, cwd=cwd, env=env,
            stdout=self._log, stderr=subprocess.STDOUT)
        self._stopped = False
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _update(self, **fields):
        cur = self._rt.cluster.kv_get(self.job_id, ns=_KV_NS) or {}
        cur.update(fields)
        cur.setdefault("job_id", self.job_id)
        cur.setdefault("entrypoint", self.entrypoint)
        cur["log_path"] = getattr(self, "log_path", "")
        self._rt.cluster.kv_put(self.job_id, cur, ns=_KV_NS)

    def _wait(self):
        rc = self._proc.wait()
        self._log.close()
        if self._stopped:
            status = "STOPPED"
        else:
            status = "SUCCEEDED" if rc == 0 else "FAILED"
        self._update(status=status, return_code=rc,
                     end_time=time.time())

    def stop(self) -> bool:
        self._stopped = True
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        # The child is down (or killed): the waiter's wait() returns,
        # closes the log, and records the final status — reap it.
        self._waiter.join(timeout=5.0)
        return True

    def logs(self, tail_bytes: int = 1 << 20) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, 2)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def poll(self) -> Optional[int]:
        return self._proc.poll()


def submit_job(entrypoint: str, *,
               runtime_env: Optional[Dict[str, Any]] = None,
               submission_id: Optional[str] = None) -> str:
    """Start a job; returns its id (reference: POST /api/jobs/,
    job_head.py:329 → JobManager.submit_job)."""
    import ray_tpu

    rt = ray_tpu.get_runtime()
    if rt.cluster is None:
        raise RuntimeError("job submission needs a cluster "
                           "(ray_tpu.init(address=...))")
    job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
    rt.cluster.kv_put(job_id, {
        "job_id": job_id, "entrypoint": entrypoint,
        "status": "PENDING", "submit_time": time.time(),
    }, ns=_KV_NS)
    import ray_tpu as _r

    _r.remote(JobSupervisor).options(
        name=f"_job_supervisor:{job_id}", lifetime="detached",
    ).remote(job_id, entrypoint, runtime_env)
    return job_id


def get_job_info(job_id: str) -> Dict[str, Any]:
    import ray_tpu

    info = ray_tpu.get_runtime().cluster.kv_get(job_id, ns=_KV_NS)
    if info is None:
        raise KeyError(f"no such job {job_id!r}")
    return info


def get_job_status(job_id: str) -> str:
    return get_job_info(job_id)["status"]


def get_job_logs(job_id: str) -> str:
    import ray_tpu

    try:
        sup = ray_tpu.get_actor(f"_job_supervisor:{job_id}")
        return ray_tpu.get(sup.logs.remote(), timeout=30)
    except Exception:
        # Supervisor gone (job long finished): read the file directly
        # if it is local.
        info = get_job_info(job_id)
        path = info.get("log_path")
        if path and os.path.exists(path):
            with open(path, "rb") as f:
                return f.read().decode(errors="replace")
        return ""


def stop_job(job_id: str) -> bool:
    import ray_tpu

    try:
        sup = ray_tpu.get_actor(f"_job_supervisor:{job_id}")
    except Exception:
        return False
    return ray_tpu.get(sup.stop.remote(), timeout=30)


def list_jobs() -> List[Dict[str, Any]]:
    import ray_tpu

    cluster = ray_tpu.get_runtime().cluster
    out = []
    for key in cluster.kv_keys(ns=_KV_NS):
        info = cluster.kv_get(key, ns=_KV_NS)
        if info:
            out.append(info)
    return sorted(out, key=lambda j: j.get("submit_time", 0))


def wait_job(job_id: str, timeout: float = 300.0,
             poll_s: float = 0.25) -> str:
    """Block until the job reaches a terminal status."""
    deadline = time.monotonic() + timeout
    status = get_job_status(job_id)
    while time.monotonic() < deadline:
        status = get_job_status(job_id)
        if status in ("SUCCEEDED", "FAILED", "STOPPED"):
            return status
        time.sleep(poll_s)
    raise TimeoutError(f"job {job_id} still {status} after {timeout}s")
