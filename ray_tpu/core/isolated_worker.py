"""Child entry for pooled isolated workers (core/isolated_pool.py).

One OS process per worker: a segfaulting C extension, an OOMing task,
or a GIL-hogging loop dies HERE, not in the node process (reference:
every Ray worker is a process — src/ray/raylet/worker_pool.h:216; this
build makes isolation opt-in since the common case shares the node's
jax runtime).

Protocol over stdin/stdout pipes, 4-byte big-endian length framing,
payloads via cluster.serialization (array/bf16-aware two-pickle):

  child -> parent  {"ready": pid}                       (startup handshake)
  parent -> child  {"op": "task", "fn", "args", "kwargs"}
                   {"op": "init", "cls", "args", "kwargs"}
                   {"op": "call", "method", "args", "kwargs"}
                   {"op": "exit"}
  child -> parent  {"ok": value} | {"err": exception}

The child NEVER touches the TPU: JAX_PLATFORMS is forced to cpu before
any user code runs (the parent process owns the chip; a second process
attaching would wedge the runtime).
"""

from __future__ import annotations

import os
import struct
import sys


def _read_exact(stream, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            raise EOFError("parent closed the pipe")
        buf += chunk
    return buf


def read_frame(stream):
    from ray_tpu.cluster.serialization import loads

    (n,) = struct.unpack(">I", _read_exact(stream, 4))
    return loads(_read_exact(stream, n))


def write_frame(stream, payload) -> None:
    from ray_tpu.cluster.serialization import dumps

    data = dumps(payload)
    stream.write(struct.pack(">I", len(data)) + data)
    stream.flush()


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"
    inp = sys.stdin.buffer
    # Reserve fd 1 for the protocol; user prints go to stderr so they
    # cannot corrupt framing.
    out = os.fdopen(os.dup(1), "wb")
    sys.stdout = sys.stderr

    write_frame(out, {"ready": os.getpid()})
    instance = None
    while True:
        try:
            msg = read_frame(inp)
        except EOFError:
            return
        op = msg.get("op")
        if op == "exit":
            return
        try:
            if op == "task":
                result = msg["fn"](*msg["args"], **msg["kwargs"])
            elif op == "init":
                instance = msg["cls"](*msg["args"], **msg["kwargs"])
                result = None
            elif op == "call":
                result = getattr(instance, msg["method"])(
                    *msg["args"], **msg["kwargs"])
            else:
                raise ValueError(f"unknown op {op!r}")
            reply = {"ok": result}
        except BaseException as e:  # noqa: BLE001
            reply = {"err": e}
        try:
            write_frame(out, reply)
        except Exception:
            # Unpicklable result/exception: degrade to a repr error.
            bad = reply["ok"] if "ok" in reply else reply["err"]
            write_frame(out, {"err": RuntimeError(
                f"isolated worker result not serializable: "
                f"{type(bad).__name__}")})


if __name__ == "__main__":
    main()
