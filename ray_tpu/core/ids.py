"""Binary IDs with lineage-encoded ObjectIDs.

Reference semantics: src/ray/common/id.h — JobID (4 bytes), ActorID
(JobID + 12 random bytes), TaskID (ActorID + 8 bytes), ObjectID
(TaskID + 4-byte index), NodeID / WorkerID / PlacementGroupID (random 28B).
The key property preserved here is that an ObjectID embeds the TaskID that
created it (lineage), and a TaskID embeds the ActorID/JobID it belongs to —
this is what makes lineage reconstruction and ownership routing possible
without a global lookup.
"""

from __future__ import annotations

import os
import threading

_JOB_ID_SIZE = 4
_ACTOR_UNIQUE_BYTES = 12
_TASK_UNIQUE_BYTES = 8
_OBJECT_INDEX_BYTES = 4

ACTOR_ID_SIZE = _JOB_ID_SIZE + _ACTOR_UNIQUE_BYTES            # 16
TASK_ID_SIZE = ACTOR_ID_SIZE + _TASK_UNIQUE_BYTES             # 24
OBJECT_ID_SIZE = TASK_ID_SIZE + _OBJECT_INDEX_BYTES           # 28
UNIQUE_ID_SIZE = 28


class BaseID:
    """Immutable binary identifier."""

    SIZE = UNIQUE_ID_SIZE
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got "
                f"{len(binary) if isinstance(binary, bytes) else type(binary)}"
            )
        object.__setattr__(self, "_binary", binary)
        object.__setattr__(self, "_hash", hash((type(self).__name__, binary)))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __setattr__(self, *a):
        raise AttributeError(f"{type(self).__name__} is immutable")

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(self) is type(other) and self._binary == other._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]}…)"

    def __reduce__(self):
        return (type(self), (self._binary,))


class UniqueID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class NodeID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class WorkerID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(_JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._binary, "little")


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(_ACTOR_UNIQUE_BYTES))

    @classmethod
    def nil_for_job(cls, job_id: JobID) -> "ActorID":
        # The "no actor" actor id for a job: job bytes + 0xff padding.
        return cls(job_id.binary() + b"\xff" * _ACTOR_UNIQUE_BYTES)

    def job_id(self) -> JobID:
        return JobID(self._binary[:_JOB_ID_SIZE])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE

    @classmethod
    def for_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + os.urandom(_TASK_UNIQUE_BYTES))

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        # The driver's implicit root task: nil actor, zero unique bytes.
        return cls(
            ActorID.nil_for_job(job_id).binary() + b"\x00" * _TASK_UNIQUE_BYTES
        )

    def actor_id(self) -> ActorID:
        return ActorID(self._binary[:ACTOR_ID_SIZE])

    def job_id(self) -> JobID:
        return JobID(self._binary[:_JOB_ID_SIZE])


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        """Lineage encoding: the creating task's id + return index."""
        if index < 0 or index >= 2 ** (_OBJECT_INDEX_BYTES * 8):
            raise ValueError(f"return index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(_OBJECT_INDEX_BYTES, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Puts share the index space with returns, offset into the top half
        # (reference: id.h ObjectID::FromIndex with put vs return bit).
        return cls.for_return(task_id, 2 ** (_OBJECT_INDEX_BYTES * 8 - 1) + put_index)

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:TASK_ID_SIZE])

    def job_id(self) -> JobID:
        return JobID(self._binary[:_JOB_ID_SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._binary[TASK_ID_SIZE:], "little")

    def is_put(self) -> bool:
        return self.return_index() >= 2 ** (_OBJECT_INDEX_BYTES * 8 - 1)


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
