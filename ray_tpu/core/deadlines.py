"""End-to-end request deadlines (the overload-robust request plane).

Following "The Tail at Scale" (Dean & Barroso, CACM 2013): under
saturation a system must degrade by *shedding* work that can no longer
meet its deadline, not by letting queues and latency grow without
bound.  The primitive here is an ABSOLUTE deadline (``time.time()``
epoch seconds — monotonic clocks don't compare across processes)
minted once at the ingress/driver root op and carried next to the
trace id on every hop:

- ``TaskSpec.deadline`` — set from ``.options(deadline_s=...)`` or
  inherited from the ambient scope at submission
  (:func:`for_submission`, mirroring ``tracing.for_submission``).
- the RPC envelope's 5th field (``cluster/rpc.py``) — the server
  re-installs it around the handler (:func:`scope_from`), so task
  submissions on the receiving node inherit the caller's budget.
- ``TaskContext.deadline`` — executing user code can read its own
  remaining budget, and anything it submits or ``get``s inherits it.

Every dequeue point (scheduler dispatch, actor mailbox, batch flush)
sheds already-expired work with a typed ``DeadlineExceededError``
instead of executing it; the shed is counted in
``ray_tpu_requests_expired_shed``.

Clock-skew caveat: cross-host deadlines assume loosely-synchronized
wall clocks (NTP-level skew is noise against second-scale serving
deadlines).
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional

# A ContextVar, NOT threading.local: async actors run many requests
# interleaved on ONE event-loop thread, and a thread-local installed
# around awaits would leak one request's deadline into another's
# resumed coroutine (poisoning its get()/submissions).  Each asyncio
# Task gets its own context copy at creation, so per-task writes stay
# per-task; on plain threads a ContextVar behaves like a thread-local.
_deadline_var: contextvars.ContextVar[Optional[float]] = \
    contextvars.ContextVar("ray_tpu_deadline", default=None)


def current() -> Optional[float]:
    """The ambient absolute deadline (epoch s) of this thread/task,
    or None."""
    return _deadline_var.get()


def set_current(deadline: Optional[float]) -> Optional[float]:
    """Install ``deadline`` in the current context; returns the
    previous value so callers can restore it (always restore — server
    handler and executor threads are reused across requests)."""
    prev = _deadline_var.get()
    _deadline_var.set(deadline)
    return prev


class scope:
    """``with deadlines.scope(dl): ...`` — install ``dl`` (which may be
    None, clearing any stale ambient deadline) and restore on exit."""

    __slots__ = ("_deadline", "_prev")

    def __init__(self, deadline: Optional[float]):
        self._deadline = deadline

    def __enter__(self):
        self._prev = set_current(self._deadline)
        return self._deadline

    def __exit__(self, *exc):
        set_current(self._prev)


def scope_from(deadline: Optional[float]) -> "scope":
    """Alias used at RPC-handler re-installation sites (parallel to
    ``tracing.scope_from``)."""
    return scope(deadline)


def for_submission(deadline_s: Optional[float]) -> Optional[float]:
    """The absolute deadline for a spec being minted NOW: an explicit
    ``deadline_s`` option wins (relative to now); else inherit the
    ambient deadline (a parent task's / RPC caller's budget)."""
    if deadline_s is not None:
        return time.time() + float(deadline_s)
    return current()


def remaining(deadline: Optional[float]) -> Optional[float]:
    """Seconds of budget left (may be <= 0), or None for no deadline."""
    if deadline is None:
        return None
    return deadline - time.time()


def expired(deadline: Optional[float]) -> bool:
    return deadline is not None and time.time() >= deadline
