"""Resource accounting.

Reference semantics: src/ray/common/scheduling/ — a node advertises a
total resource set ({"CPU": n, "TPU": m, custom...}); tasks demand
resources which are acquired at dispatch and released at completion.
TPU note: nodes can carry placement labels (see NodeLabel scheduling
in cluster/head.py); ICI-topology-aware labels are not auto-detected
yet — pass them explicitly at node start.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class ResourceSet:
    def __init__(self, total: Dict[str, float]):
        self._total = {k: float(v) for k, v in total.items() if v}
        self._available = dict(self._total)
        self._cond = threading.Condition()

    @property
    def total(self) -> Dict[str, float]:
        return dict(self._total)

    def available(self) -> Dict[str, float]:
        with self._cond:
            return dict(self._available)

    def can_ever_fit(self, demand: Dict[str, float]) -> bool:
        return all(self._total.get(k, 0.0) >= v for k, v in demand.items())

    def fits_now(self, demand: Dict[str, float]) -> bool:
        with self._cond:
            return all(self._available.get(k, 0.0) >= v - 1e-9
                       for k, v in demand.items())

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        with self._cond:
            if all(self._available.get(k, 0.0) >= v - 1e-9
                   for k, v in demand.items()):
                for k, v in demand.items():
                    self._available[k] = self._available.get(k, 0.0) - v
                return True
            return False

    def acquire(self, demand: Dict[str, float],
                timeout: Optional[float] = None) -> bool:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: all(self._available.get(k, 0.0) >= v - 1e-9
                            for k, v in demand.items()),
                timeout,
            )
            if not ok:
                return False
            for k, v in demand.items():
                self._available[k] = self._available.get(k, 0.0) - v
            return True

    def release(self, demand: Dict[str, float]):
        with self._cond:
            for k, v in demand.items():
                self._available[k] = min(
                    self._total.get(k, 0.0), self._available.get(k, 0.0) + v
                )
            self._cond.notify_all()

    def add_capacity(self, extra: Dict[str, float]):
        """Used by placement groups to mint bundle resources."""
        with self._cond:
            for k, v in extra.items():
                self._total[k] = self._total.get(k, 0.0) + v
                self._available[k] = self._available.get(k, 0.0) + v
            self._cond.notify_all()

    def remove_capacity(self, extra: Dict[str, float]):
        with self._cond:
            for k, v in extra.items():
                self._total[k] = max(0.0, self._total.get(k, 0.0) - v)
                self._available[k] = max(
                    0.0, self._available.get(k, 0.0) - v)
            self._cond.notify_all()


def detect_node_resources(num_cpus: Optional[float] = None,
                          num_tpus: Optional[float] = None,
                          resources: Optional[Dict[str, float]] = None
                          ) -> Dict[str, float]:
    """Auto-detect this host's resources (reference:
    _private/accelerators/tpu.py detects TPU chips via env/libtpu)."""
    import os

    total: Dict[str, float] = {}
    total["CPU"] = float(num_cpus if num_cpus is not None
                         else os.cpu_count() or 1)
    if num_tpus is None:
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # CPU-forced process: no TPUs by construction.  Probing
            # would initialize the jax backend, which must stay
            # untouched until a possible jax.distributed.initialize
            # (multi-host train bootstrap requires init-before-backend).
            num_tpus = 0.0
        else:
            try:
                import jax

                num_tpus = float(len([d for d in jax.devices()
                                      if d.platform != "cpu"]))
            except Exception:
                num_tpus = 0.0
    if num_tpus:
        total["TPU"] = float(num_tpus)
    total["memory"] = float(_detect_memory_bytes())
    if resources:
        total.update({k: float(v) for k, v in resources.items()})
    return total


def _detect_memory_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 * 1024**3
