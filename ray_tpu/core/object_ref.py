"""ObjectRef: a distributed future addressing one immutable object.

Reference semantics: ObjectRef in python/ray/includes/object_ref.pxi +
ownership in src/ray/core_worker/reference_count.h:64.  A ref is created
eagerly at submission time (ObjectID = TaskID + index, lineage encoded),
before the value exists; ``get`` blocks until the value is sealed in the
owner's store.  Refs participate in distributed GC: the runtime is told
when Python drops the last local reference.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Optional

from .ids import ObjectID

# Deferred release queue: ``__del__`` runs inside the garbage collector
# at ARBITRARY points — including while the collecting thread holds the
# object-store lock (a dict insert in ``put`` can trigger GC) — so the
# release path (store free, plasma free, borrower-release RPCs!) must
# never run inline there.  __del__ only appends to this deque
# (GIL-atomic, lock-free); a reaper thread drains it.
_pending_releases: "collections.deque" = collections.deque()


def _release_loop():
    while True:
        try:
            rt, oid = _pending_releases.popleft()
        except IndexError:
            # Plain polling on purpose: an Event/Condition set from
            # __del__ could re-enter its own (non-reentrant) lock if GC
            # fires inside a notify — the very deadlock this thread
            # exists to avoid.  50 ms idle latency is invisible to the
            # GC-driven release path.
            time.sleep(0.05)
            continue
        if rt.is_shutdown:
            continue
        try:
            rt.reference_counter.remove_local_reference(oid)
        except Exception:
            pass


_reaper = threading.Thread(target=_release_loop, daemon=True,
                           name="raytpu-ref-reaper")
_reaper.start()


class ObjectRef:
    __slots__ = ("_id", "_owner", "_call_site", "_runtime", "__weakref__")

    def __init__(self, object_id: ObjectID, runtime=None, owner: str = "",
                 call_site: str = "", add_local_ref: bool = True):
        self._id = object_id
        self._owner = owner
        self._call_site = call_site
        self._runtime = runtime
        if runtime is not None and add_local_ref:
            runtime.reference_counter.add_local_reference(object_id)

    def object_id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    def task_id(self):
        return self._id.task_id()

    def owner_address(self) -> str:
        return self._owner

    def call_site(self) -> str:
        return self._call_site

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and self._id == other._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        rt = self._runtime
        if rt is not None and not rt.is_shutdown:
            _pending_releases.append((rt, self._id))

    # Futures protocol -------------------------------------------------------
    def future(self) -> "threading.Event":
        return self._runtime.object_store.completion_event(self._id)

    def _on_completed(self, callback: Callable[[Any], None]):
        """Invoke callback with the sealed RayObject (value or error)."""
        self._runtime.object_store.add_done_callback(self._id, callback)

    def __await__(self):
        # Asyncio interop: ray.get in a thread to avoid blocking the loop.
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.create_future()

        def _done(obj):
            def _set():
                if fut.cancelled():
                    return
                err = obj.error
                if err is not None:
                    fut.set_exception(err)
                else:
                    fut.set_result(obj.value)

            loop.call_soon_threadsafe(_set)

        self._on_completed(_done)
        return fut.__await__()

    def __reduce__(self):
        # Serializing a ref ships the id + owner address; the receiving
        # runtime re-registers it and can fetch the value from the owner
        # (borrower protocol, simplified: no distributed ref counts yet).
        owner = self._owner
        if not owner and self._runtime is not None:
            owner = getattr(self._runtime, "address", "") or ""
        return (_deserialize_ref, (self._id, owner, self._call_site))


def _deserialize_ref(object_id, owner, call_site):
    from .runtime import try_get_runtime

    rt = try_get_runtime()
    return ObjectRef(object_id, rt, owner, call_site)


class ObjectRefGenerator:
    """Streaming-generator handle (reference: _raylet.pyx:284 — tasks with
    ``num_returns="streaming"``).  Iterating yields ObjectRefs as the
    executor reports them; supports both sync and async iteration."""

    def __init__(self, generator_id: ObjectID, runtime):
        self._generator_id = generator_id
        self._runtime = runtime
        self._index = 0
        self._lock = threading.Lock()

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self.next_ref()

    def next_ref(self, timeout: Optional[float] = None) -> ObjectRef:
        """``__next__`` with an optional bound on the item wait.  On
        timeout raises ``GetTimeoutError`` and puts the index back, so
        a later call retries the same item (single-consumer iteration
        assumed, as with any generator)."""
        with self._lock:
            idx = self._index
            self._index += 1
        try:
            item_id = self._runtime.streaming_manager.wait_item(
                self._generator_id, idx, timeout)
        except TimeoutError:
            with self._lock:
                self._index -= 1
            from ..exceptions import GetTimeoutError

            raise GetTimeoutError(
                f"streaming item {idx} not reported within {timeout}s")
        if item_id is None:
            raise StopIteration
        return ObjectRef(item_id, self._runtime)

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        import asyncio

        loop = asyncio.get_event_loop()
        try:
            return await loop.run_in_executor(None, self.__next__)
        except StopIteration:
            raise StopAsyncIteration

    def completed(self) -> bool:
        return self._runtime.streaming_manager.is_finished(self._generator_id)
