"""In-memory object store: the owner's view of object values.

Reference semantics: the core-worker in-process memory store
(src/ray/core_worker/store_provider/memory_store/memory_store.h:43) —
small/inlined results live here; big values live in the node's shared
store (ray_tpu.core.plasma, cluster mode).  Objects are immutable once
sealed; sealing fires completion callbacks (get waiters, dependency
resolution, streaming consumers).

TPU note: values may be ``jax.Array``s.  They are kept by reference (no
copy, no host transfer) so HBM-resident arrays flow between tasks on the
same process at zero cost; cross-process transfer goes through the
serialization layer which devices-gets only at the boundary.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .ids import ObjectID
from ..exceptions import GetTimeoutError, ObjectFreedError

_UNSET = object()


class RayObject:
    """A sealed object: exactly one of sealed-value / error / remote
    location is meaningful.

    Values are sealed through the serialization boundary at put time
    (cluster/serialization.py): each ``value`` access deserializes a
    fresh copy of the container structure, so a consumer mutating a
    ``get`` result can never alias the producer's copy or another
    consumer's (reference plasma semantics).  Array leaves are shared —
    jax.Arrays by reference (immutable), numpy as frozen read-only
    copies.

    A *location record* (``location=(node_id, address)``) is the owner's
    view of a primary copy pinned on the executing node (reference:
    plasma-resident big task returns + ownership-based object directory,
    ownership_based_object_directory.h).  ``get`` materializes it via a
    chunked pull; losing the holder triggers lineage reconstruction.
    """

    __slots__ = ("sealed", "error", "size_bytes", "location")

    def __init__(self, value: Any = _UNSET, error: Optional[BaseException] = None,
                 size_bytes: Optional[int] = None, sealed=None,
                 location: Optional[tuple] = None):
        if sealed is not None:
            self.sealed = sealed
        elif value is not _UNSET:
            from ..cluster.serialization import serialize

            self.sealed = serialize(value)
        else:
            self.sealed = None
        self.error = error
        self.location = location
        if size_bytes is None:
            size_bytes = self.sealed.size_bytes if self.sealed else 0
        self.size_bytes = size_bytes

    @property
    def value(self) -> Any:
        if self.sealed is None:
            if self.location is not None:
                raise RuntimeError(
                    "located object was not materialized before value "
                    "access (runtime.get pulls it first)")
            return None
        from ..cluster.serialization import deserialize

        return deserialize(self.sealed)

    def is_error(self) -> bool:
        return self.error is not None

    def is_located_only(self) -> bool:
        return (self.sealed is None and self.error is None
                and self.location is not None)


class MemoryStore:
    """Thread-safe object table with completion events + callbacks."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, RayObject] = {}
        self._events: Dict[ObjectID, threading.Event] = {}
        self._callbacks: Dict[ObjectID, List[Callable[[RayObject], None]]] = {}
        self._total_bytes = 0

    # -- write side ----------------------------------------------------------
    def put(self, object_id: ObjectID, obj: RayObject) -> None:
        with self._lock:
            if object_id in self._objects:
                # Objects are immutable: double-seal keeps the first value.
                # (Happens on speculative retries racing a slow original.)
                return
            self._objects[object_id] = obj
            self._total_bytes += obj.size_bytes
            event = self._events.pop(object_id, None)
            callbacks = self._callbacks.pop(object_id, [])
        if event is not None:
            event.set()
        for cb in callbacks:
            cb(obj)

    def free(self, object_id: ObjectID) -> None:
        with self._lock:
            obj = self._objects.pop(object_id, None)
            if obj is not None:
                self._total_bytes -= obj.size_bytes
            self._events.pop(object_id, None)
            self._callbacks.pop(object_id, None)

    def replace_with_error(self, object_id: ObjectID, error: BaseException):
        """Used by GC/eviction to leave a tombstone."""
        with self._lock:
            old = self._objects.pop(object_id, None)
            if old is not None:
                self._total_bytes -= old.size_bytes
            self._objects[object_id] = RayObject(error=error)

    def materialize(self, object_id: ObjectID, sealed) -> None:
        """Attach the pulled value to a location record in place (the
        entry keeps its location so later borrowers can still be
        redirected).  No-op if the entry is gone or already sealed."""
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None or obj.sealed is not None or obj.is_error():
                return
            obj.sealed = sealed
            self._total_bytes += sealed.size_bytes - obj.size_bytes
            obj.size_bytes = sealed.size_bytes

    def invalidate_for_recovery(self, object_id: ObjectID) -> None:
        """Drop a stale location record so a reconstruction re-seal can
        land.  Unlike ``free``, registered waiter events and callbacks
        stay: the recovery ``put`` fires them."""
        with self._lock:
            obj = self._objects.pop(object_id, None)
            if obj is not None:
                self._total_bytes -= obj.size_bytes

    # -- read side -----------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_if_exists(self, object_id: ObjectID) -> Optional[RayObject]:
        with self._lock:
            return self._objects.get(object_id)

    def completion_event(self, object_id: ObjectID) -> threading.Event:
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                ev = threading.Event()
                ev.set()
                return ev
            ev = self._events.get(object_id)
            if ev is None:
                ev = threading.Event()
                self._events[object_id] = ev
            return ev

    def add_done_callback(self, object_id: ObjectID,
                          callback: Callable[[RayObject], None]):
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                self._callbacks.setdefault(object_id, []).append(callback)
                return
        callback(obj)

    def wait_and_get(self, object_id: ObjectID,
                     timeout: Optional[float] = None) -> RayObject:
        ev = self.completion_event(object_id)
        if not ev.wait(timeout):
            raise GetTimeoutError(
                f"get() timed out after {timeout}s waiting for {object_id!r}"
            )
        with self._lock:
            obj = self._objects.get(object_id)
        if obj is None:
            # Freed between event set and read.
            raise ObjectFreedError(reason=f"{object_id!r} was freed")
        return obj

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "total_bytes": self._total_bytes,
                "num_waiters": len(self._events),
            }


def wait_refs(store: MemoryStore, object_ids, num_returns: int,
              timeout: Optional[float]):
    """Core of ``ray.wait``: first-completed ordering, stable within ready.

    Reference: CoreWorker::Wait (core_worker.cc:1901) — returns
    (ready, not_ready) preserving input order among the ready set.
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    ready: List = []
    pending = list(object_ids)
    done = threading.Event()
    lock = threading.Lock()

    def make_cb(oid):
        def cb(_obj):
            with lock:
                if oid not in ready:
                    ready.append(oid)
                if len(ready) >= num_returns:
                    done.set()

        return cb

    for oid in pending:
        store.add_done_callback(oid, make_cb(oid))

    if deadline is None:
        done.wait()
    else:
        done.wait(max(0.0, deadline - time.monotonic()))

    with lock:
        ready_set = set(ready[:num_returns])
    ready_ordered = [o for o in object_ids if o in ready_set]
    not_ready = [o for o in object_ids if o not in ready_set]
    return ready_ordered, not_ready
