"""@remote functions.

Reference semantics: python/ray/remote_function.py:41,303 — the decorator
wraps a function into a handle whose ``.remote(...)`` submits a task and
returns ObjectRef futures; ``.options(...)`` overrides submission options
per call-site; calling the function directly raises (push users toward
explicit remote/local split).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

from .runtime import get_runtime
from .task_spec import TaskOptions, STREAMING

_OPTION_KEYS = {
    "num_returns", "num_cpus", "num_tpus", "num_gpus", "resources",
    "max_retries", "retry_exceptions", "scheduling_strategy", "name",
    "runtime_env", "memory", "_metadata", "concurrency_group",
    "isolate", "deadline_s",
}


def _build_options(defaults: Dict[str, Any],
                   overrides: Dict[str, Any]) -> TaskOptions:
    merged = dict(defaults)
    merged.update(overrides)
    unknown = set(merged) - _OPTION_KEYS
    if unknown:
        raise ValueError(f"unknown options: {sorted(unknown)}")
    # num_gpus is accepted as an alias for TPU-less portability of user
    # code; it maps onto the generic accelerator resource.
    num_tpus = merged.get("num_tpus")
    if num_tpus is None and merged.get("num_gpus") is not None:
        num_tpus = merged["num_gpus"]
    resources = dict(merged.get("resources") or {})
    if merged.get("memory"):
        resources["memory"] = float(merged["memory"])
    return TaskOptions(
        num_returns=merged.get("num_returns", 1),
        num_cpus=merged.get("num_cpus"),
        num_tpus=num_tpus,
        resources=resources,
        max_retries=merged.get("max_retries", 3),
        retry_exceptions=merged.get("retry_exceptions", False),
        scheduling_strategy=merged.get("scheduling_strategy"),
        name=merged.get("name", ""),
        runtime_env=merged.get("runtime_env"),
        isolate=bool(merged.get("isolate", False)),
        deadline_s=merged.get("deadline_s"),
        _metadata=merged.get("_metadata") or {},
    )


class RemoteFunction:
    def __init__(self, function: Callable, default_options: Dict[str, Any]):
        self._function = function
        self._default_options = default_options
        functools.update_wrapper(self, function)

    def remote(self, *args, **kwargs):
        return self._submit(args, kwargs, {})

    def options(self, **overrides) -> "_OptionsHandle":
        return _OptionsHandle(self, overrides)

    def _submit(self, args, kwargs, overrides):
        options = _build_options(self._default_options, overrides)
        return get_runtime().submit_task(self._function, args, kwargs,
                                         options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function.__name__} cannot be called "
            f"directly — use .remote() (or access the original via "
            f".bound_function)")

    @property
    def bound_function(self) -> Callable:
        return self._function

    def bind(self, *args, **kwargs):
        """DAG-node construction (compiled-graph API; reference
        dag/dag_node.py). Returns a FunctionNode for lazy composition."""
        from ..dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)


class _OptionsHandle:
    def __init__(self, remote_fn: RemoteFunction, overrides: Dict[str, Any]):
        self._remote_fn = remote_fn
        self._overrides = overrides

    def remote(self, *args, **kwargs):
        return self._remote_fn._submit(args, kwargs, self._overrides)

    def bind(self, *args, **kwargs):
        from ..dag.dag_node import FunctionNode

        return FunctionNode(self._remote_fn, args, kwargs, self._overrides)
