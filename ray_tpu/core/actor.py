"""Actor user API: @remote classes, handles, methods.

Reference semantics: python/ray/actor.py — ActorClass (:602) with
``.remote(...)`` / ``.options(...)``, ActorHandle (:1265) whose attribute
access returns ActorMethod (:116) objects, named/detached actors, and the
``.options(name=..., get_if_exists=True)`` get-or-create pattern.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from .ids import ActorID
from .runtime import get_runtime
from .remote_function import _build_options

_ACTOR_OPTION_KEYS = {
    "name", "namespace", "lifetime", "max_restarts", "max_task_retries",
    "max_concurrency", "max_pending_calls", "num_cpus", "num_tpus",
    "num_gpus", "resources", "memory", "scheduling_strategy",
    "get_if_exists", "runtime_env", "_metadata", "isolate",
}


class ActorClass:
    def __init__(self, klass: type, default_options: Dict[str, Any]):
        self._klass = klass
        self._default_options = default_options
        functools.update_wrapper(self, klass, updated=[])

    def remote(self, *args, **kwargs) -> "ActorHandle":
        return self._create(args, kwargs, {})

    def options(self, **overrides) -> "_ActorOptionsHandle":
        unknown = set(overrides) - _ACTOR_OPTION_KEYS
        if unknown:
            raise ValueError(f"unknown actor options: {sorted(unknown)}")
        return _ActorOptionsHandle(self, overrides)

    def bind(self, *args, **kwargs):
        from ..dag.dag_node import ClassNode

        return ClassNode(self, args, kwargs)

    def _create(self, args, kwargs, overrides) -> "ActorHandle":
        merged = dict(self._default_options)
        merged.update(overrides)
        num_tpus = merged.get("num_tpus")
        if num_tpus is None and merged.get("num_gpus") is not None:
            num_tpus = merged["num_gpus"]
        return get_runtime().create_actor(
            self._klass, args, kwargs,
            name=merged.get("name", "") or "",
            namespace=merged.get("namespace"),
            max_restarts=merged.get("max_restarts", 0),
            max_task_retries=merged.get("max_task_retries", 0),
            max_concurrency=merged.get("max_concurrency"),
            max_pending_calls=merged.get("max_pending_calls", -1),
            lifetime=merged.get("lifetime"),
            num_cpus=merged.get("num_cpus"),
            num_tpus=num_tpus,
            resources=merged.get("resources"),
            scheduling_strategy=merged.get("scheduling_strategy"),
            get_if_exists=merged.get("get_if_exists", False),
            isolate=bool(merged.get("isolate", False)),
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._klass.__name__} cannot be instantiated "
            f"directly — use .remote()")

    @property
    def bound_class(self) -> type:
        return self._klass


class _ActorOptionsHandle:
    def __init__(self, actor_class: ActorClass, overrides: Dict[str, Any]):
        self._actor_class = actor_class
        self._overrides = overrides

    def remote(self, *args, **kwargs) -> "ActorHandle":
        return self._actor_class._create(args, kwargs, self._overrides)

    def bind(self, *args, **kwargs):
        from ..dag.dag_node import ClassNode

        return ClassNode(self._actor_class, args, kwargs, self._overrides)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 overrides: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._method_name = method_name
        self._overrides = overrides or {}

    def remote(self, *args, **kwargs):
        # Per-method defaults from @ray_tpu.method(...) sit between the
        # built-in defaults and .options() overrides.
        method = getattr(self._handle._klass, self._method_name, None)
        decorated = getattr(method, "__ray_tpu_method_options__", {})
        options = _build_options({"max_retries": 0, **decorated},
                                 self._overrides)
        return get_runtime().submit_actor_task(
            self._handle._actor_id, self._method_name, args, kwargs,
            options, klass=self._handle._klass)

    def options(self, **overrides) -> "ActorMethod":
        return ActorMethod(self._handle, self._method_name, overrides)

    def bind(self, *args, **kwargs):
        from ..dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._method_name} cannot be called directly — "
            f"use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, klass: type, runtime,
                 creation_ref=None):
        self._actor_id = actor_id
        self._klass = klass
        self._runtime = runtime
        # Holding the creation ref keeps creation errors retrievable.
        self._creation_ref = creation_ref

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if not callable(getattr(self._klass, name, None)):
            raise AttributeError(
                f"{self._klass.__name__} has no method {name!r}")
        return ActorMethod(self, name)

    def _actor_ready(self, timeout: Optional[float] = None):
        """Block until the constructor finished (raises on failure)."""
        core = self._runtime.actor_manager.get_core(self._actor_id)
        if core is not None:
            core.wait_ready(timeout)
        elif self._runtime.cluster is not None:
            self._runtime.cluster.wait_remote_actor_ready(
                self._actor_id, timeout)

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __repr__(self):
        return (f"ActorHandle({self._klass.__name__}, "
                f"{self._actor_id.hex()[:16]})")

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id, self._klass))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return (isinstance(other, ActorHandle)
                and self._actor_id == other._actor_id)


def _rebuild_handle(actor_id, klass):
    from .runtime import get_runtime

    return ActorHandle(actor_id, klass, get_runtime())


def exit_actor():
    """Terminate the current actor from inside one of its methods
    (reference: ray.actor.exit_actor)."""
    from .actor_runtime import ActorExitSignal
    from . import runtime_context as rc

    ctx = rc.current_task_context()
    if ctx is None or ctx.actor_id is None:
        raise RuntimeError("exit_actor() called outside an actor method")
    raise ActorExitSignal()
