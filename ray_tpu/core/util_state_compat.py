"""Node-local state snapshots served over the node RPC
(``node_state``): the task/object halves of the state API, gathered
per node by the CLI (reference: util/state backed by per-node agents +
GCS task events).  Thin shim over ray_tpu.util.state, which reads the
LOCAL runtime — exactly what a per-node RPC handler wants.

Filters (``trace_id``, ``state``) are applied HERE, node-side, before
the reply crosses the wire — the state API's predicate pushdown
(reference: server-side filtering in the state aggregator), so a
``ray_tpu list tasks --trace-id X`` over a busy cluster ships only
the matching rows.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def node_state(runtime, what: str,
               filters: Optional[Dict[str, Any]] = None):
    from ray_tpu.util import state

    filters = filters or {}
    if what == "tasks":
        # Any task filter implies the caller wants the full picture —
        # a --state FINISHED query over pending-only rows would
        # silently return nothing.
        tasks = state.list_tasks(
            include_done=bool(filters.get("trace_id")
                              or filters.get("state")
                              or filters.get("include_done")))
        trace_id = filters.get("trace_id")
        if trace_id is not None:
            tasks = [t for t in tasks
                     if t.get("trace_id") == trace_id]
        want_state = filters.get("state")
        if want_state is not None:
            tasks = [t for t in tasks
                     if t.get("state") == str(want_state).upper()]
        return {"pending": tasks,
                "summary": state.summarize_tasks()}
    if what == "objects":
        return {"objects": state.list_objects()[:200],
                "plasma": runtime.plasma.stats()}
    raise ValueError(f"unknown node_state {what!r}")
