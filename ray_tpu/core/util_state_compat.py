"""Node-local state snapshots served over the node RPC
(``node_state``): the task/object halves of the state API, gathered
per node by the CLI (reference: util/state backed by per-node agents +
GCS task events).  Thin shim over ray_tpu.util.state, which reads the
LOCAL runtime — exactly what a per-node RPC handler wants.
"""

from __future__ import annotations


def node_state(runtime, what: str):
    from ray_tpu.util import state

    if what == "tasks":
        return {"pending": state.list_tasks(),
                "summary": state.summarize_tasks()}
    if what == "objects":
        return {"objects": state.list_objects()[:200],
                "plasma": runtime.plasma.stats()}
    raise ValueError(f"unknown node_state {what!r}")
