"""Core runtime: tasks, actors, objects, scheduling.

TPU-native rethink of Ray core (reference: src/ray/core_worker/,
src/ray/raylet/, src/ray/gcs/ — see SURVEY.md §1 L0-L6).  The compute data
plane is jax/XLA (HBM-resident ``jax.Array`` objects, ICI collectives); the
control plane here is a single-controller runtime with pluggable executors
(in-process threads for local mode, worker processes over sockets for
cluster mode).
"""
