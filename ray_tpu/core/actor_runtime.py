"""Actor execution: per-actor ordered queues, concurrency, restarts.

Reference semantics:
- Server side: TaskReceiver + scheduling queues — sequential by default,
  threaded pool when ``max_concurrency > 1``, asyncio event loop for
  async actors (src/ray/core_worker/transport/task_receiver.h:51,
  actor_scheduling_queue.h, concurrency_group_manager.h, fiber.h).
- Control: GCS actor FSM DEPENDENCIES_UNREADY → PENDING_CREATION → ALIVE
  → RESTARTING/DEAD with ``max_restarts`` (gcs_actor_manager.h:308).
- Naming: named/detached actors in a namespace (worker.py:3010).
"""

from __future__ import annotations

import asyncio
import inspect
import queue
import threading
import time
from enum import Enum
from typing import Any, Callable, Dict, Optional, Tuple

from .ids import ActorID
from .task_spec import TaskSpec
from ..exceptions import (ActorDiedError, PendingCallsLimitExceededError,
                          TaskError)
from ..experimental import chaos as _chaos
from ..observability.profiling import stuck_guard as _stuck_guard


def _flightrec_context() -> Dict[str, Any]:
    """Context fragment pointing at this process's flight record, so a
    dead-actor error names where the local forensics live even before
    any supervisor-built postmortem bundle exists."""
    try:
        from ..observability import flightrec as _flightrec

        rec = _flightrec.current()
        if rec is not None:
            return {"flightrec": rec.base}
    except Exception:
        pass
    return {}


class ActorState(Enum):
    PENDING_CREATION = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


class ActorExitSignal(BaseException):
    """Raised by exit_actor() inside a method to terminate the actor."""


class _ActorCore:
    """One live actor: instance + its execution queue/threads."""

    def __init__(self, runtime, info: "ActorInfo"):
        self._runtime = runtime
        self.info = info
        self._queue: "queue.Queue[Optional[TaskSpec]]" = queue.Queue()
        self._threads = []
        self._stopped = threading.Event()
        # Serializes submit() vs stop() so no spec can be enqueued
        # behind the shutdown sentinels (it would hang forever).
        self._submit_lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.instance: Any = None
        self._creation_done = threading.Event()
        self._creation_error: Optional[BaseException] = None
        # Method calls queued but not yet started (decremented at dequeue);
        # the creation spec rides the same queue but must not count against
        # max_pending_calls.
        self._pending_calls = 0
        # Set by Runtime.create_actor; lets kill paths resolve a
        # still-pending creation ref.
        self.creation_spec = None

        if info.is_async:
            t = threading.Thread(target=self._async_main,
                                 name=f"actor-{info.name or info.actor_id.hex()[:8]}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        else:
            for i in range(max(1, info.max_concurrency)):
                t = threading.Thread(
                    target=self._sync_main,
                    name=f"actor-{info.name or info.actor_id.hex()[:8]}-{i}",
                    daemon=True)
                t.start()
                self._threads.append(t)

    # -- creation ------------------------------------------------------------
    def create_instance(self):
        info = self.info
        try:
            if info.isolate:
                # N8: the instance lives in a dedicated subprocess; a
                # crash there surfaces as WorkerCrashedError per call,
                # not as this node going down.
                if info.is_async:
                    raise ValueError(
                        "isolate=True does not support async actors "
                        "(coroutines cannot cross the worker process "
                        "boundary); use a sync actor or isolate=False")
                from .isolated_pool import IsolatedInstance

                self.instance = IsolatedInstance(
                    self._runtime.isolated_pool, info.klass,
                    info.init_args, info.init_kwargs)
            else:
                self.instance = info.klass(*info.init_args,
                                           **info.init_kwargs)
            info.state = ActorState.ALIVE
        except BaseException as e:  # noqa: BLE001
            self._creation_error = e
            info.state = ActorState.DEAD
        finally:
            self._creation_done.set()

    def wait_ready(self, timeout: Optional[float] = None) -> None:
        if not self._creation_done.wait(timeout):
            from ..exceptions import GetTimeoutError

            raise GetTimeoutError(
                f"actor {self.info.display_name()} not ready after "
                f"{timeout}s")
        if self._creation_error is not None:
            raise ActorDiedError(
                self.info.actor_id,
                f"actor {self.info.display_name()} failed during creation: "
                f"{self._creation_error!r}")

    # -- submission ----------------------------------------------------------
    def submit(self, spec: TaskSpec, bypass_limit: bool = False):
        """``bypass_limit``: retries of already-accepted tasks skip the
        pending-calls backpressure check (the limit is a submission-time
        contract, not a retry gate)."""
        with self._submit_lock:
            if self._stopped.is_set():
                raise self._dead_error()
            if not bypass_limit and self.info.max_pending_calls > 0 and (
                    self._pending_calls >= self.info.max_pending_calls):
                self._count_rejection()
                raise PendingCallsLimitExceededError(
                    f"actor {self.info.display_name()} has "
                    f"{self._pending_calls} pending calls "
                    f"(max_pending_calls={self.info.max_pending_calls})")
            if not spec.is_actor_creation:
                self._pending_calls += 1
                depth = self._pending_calls
            else:
                depth = None
            self._queue.put(spec)
        if depth is not None:
            self._gauge_depth(depth)

    def _count_rejection(self):
        """Bounded-mailbox admission rejection: typed AND counted, so
        the overload plane's /metrics shows where pressure lands."""
        try:
            from ..observability.metrics import overload_counters

            overload_counters()["backpressure"].inc(
                tags={"where": "max_pending_calls"})
        except Exception:
            pass

    def _gauge_depth(self, depth: int):
        try:
            from ..observability.metrics import overload_counters

            overload_counters()["queue_depth"].set(
                depth,
                tags={"queue": f"actor:{self.info.display_name()}"})
        except Exception:
            pass

    def _call_started(self, spec: TaskSpec):
        if not spec.is_actor_creation:
            with self._submit_lock:
                self._pending_calls -= 1
                depth = self._pending_calls
            self._gauge_depth(depth)

    # -- execution loops -----------------------------------------------------
    def _sync_main(self):
        while not self._stopped.is_set():
            spec = self._queue.get()
            if spec is None:
                return
            self._run_one(spec)

    def _async_main(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        sem = asyncio.Semaphore(max(1, self.info.max_concurrency))

        async def pump():
            while not self._stopped.is_set():
                spec = await self._loop.run_in_executor(None, self._queue.get)
                if spec is None:
                    return
                await sem.acquire()
                task = self._loop.create_task(self._run_one_async(spec))
                task.add_done_callback(lambda _t: sem.release())

        try:
            self._loop.run_until_complete(pump())
        finally:
            pending = asyncio.all_tasks(self._loop)
            for t in pending:
                t.cancel()
            self._loop.close()

    def _run_one(self, spec: TaskSpec):
        if spec.is_actor_creation:
            t0 = time.time()
            self.create_instance()
            self._runtime.finish_actor_creation(self, spec)
            self._runtime._record_task_event(
                spec, t0,
                "ok" if self._creation_error is None else "error")
            return
        self._call_started(spec)
        # With max_concurrency > 1 another pool thread may still be
        # running __init__: no method executes before creation settles
        # (reference: tasks queue behind actor creation).
        self._creation_done.wait()
        if self.info.state == ActorState.DEAD:
            self._runtime.task_manager.complete_error(
                spec, self._dead_error(), allow_retry=False)
            return
        # Mailbox-dequeue load shedding: work whose end-to-end deadline
        # passed while it queued completes with DeadlineExceededError —
        # user code never runs (the overload plane's core invariant).
        if self._runtime.shed_expired_spec(spec, "actor_mailbox"):
            return
        # Stuck detector (observability/profiling.py): a dispatch that
        # is still running STUCK_FACTOR x past its remaining deadline
        # budget gets every thread's stack snapshotted — the deadline
        # plane promises the caller an answer by then, so overshooting
        # it this far means something is wedged, and the post-mortem
        # needs the stacks from the moment it happened.
        budget = (None if spec.deadline is None
                  else spec.deadline - time.time())
        with _stuck_guard("actor_dispatch", budget,
                          {"method": spec.descriptor.function_name,
                           "actor": self.info.display_name()}):
            if self._chaos_gate(spec):
                return
            self._runtime.execute_task_inline(
                spec, bound_instance=self.instance, actor_core=self)

    def _chaos_gate(self, spec: TaskSpec) -> bool:
        """Fault-injection hook before method dispatch: an active
        chaos schedule may kill this actor (with or without restart
        budget), fail just this call, or STALL it (load shaping:
        ``slow_method`` / ``stall_replica`` make this actor a hot/slow
        replica deterministically).  Returns True when the spec was
        consumed by an injected fault."""
        action = _chaos.actor_task_action(spec.descriptor.function_name,
                                          self.info.display_name())
        if action is None:
            return False
        method = spec.descriptor.function_name
        if action[0] == "slow":
            # Injected latency: the call still runs, late.  Sleeping
            # here (the dispatch path) stalls the whole actor — for an
            # async actor it blocks the event loop — which is exactly
            # the slow-replica failure mode under test.
            time.sleep(action[1])
            return False
        if action[0] == "kill":
            self._runtime.task_manager.complete_error(
                spec, ActorDiedError(
                    self.info.actor_id,
                    "chaos: actor killed before dispatch",
                    context={"method": method,
                             **_flightrec_context()}),
                allow_retry=False)
            self._runtime.kill_actor(self.info.actor_id,
                                     no_restart=action[1])
            return True
        self._runtime.task_manager.complete_error(
            spec, TaskError(spec.repr_name(), action[1]),
            allow_retry=False)
        return True

    async def _run_one_async(self, spec: TaskSpec):
        if spec.is_actor_creation:
            t0 = time.time()
            self.create_instance()
            self._runtime.finish_actor_creation(self, spec)
            self._runtime._record_task_event(
                spec, t0,
                "ok" if self._creation_error is None else "error")
            return
        self._call_started(spec)
        if not self._creation_done.is_set():
            # Creation runs synchronously on this loop, so normally it
            # finished before any method task started; guard anyway
            # without blocking the loop.
            await self._loop.run_in_executor(
                None, self._creation_done.wait)
        if self.info.state == ActorState.DEAD:
            self._runtime.task_manager.complete_error(
                spec, self._dead_error(), allow_retry=False)
            return
        # Same mailbox-dequeue shed as the sync path.
        if self._runtime.shed_expired_spec(spec, "actor_mailbox"):
            return
        # Same stuck guard as the sync path: a chaos-stalled (or truly
        # wedged) async replica blocks its event loop — the snapshot
        # shows the loop thread pinned inside the stall.
        budget = (None if spec.deadline is None
                  else spec.deadline - time.time())
        with _stuck_guard("actor_dispatch", budget,
                          {"method": spec.descriptor.function_name,
                           "actor": self.info.display_name()}):
            if self._chaos_gate(spec):
                return
            await self._runtime.execute_task_inline_async(
                spec, bound_instance=self.instance, actor_core=self)

    def _dead_error(self) -> ActorDiedError:
        suffix = ""
        if self._creation_error is not None:
            suffix = f" (creation failed: {self._creation_error!r})"
        return ActorDiedError(
            self.info.actor_id,
            f"actor {self.info.display_name()} is dead{suffix}",
            node_id=self._runtime.node_id.hex(),
            context={"restarts_used": self.info.num_restarts,
                     **_flightrec_context()})

    # -- teardown ------------------------------------------------------------
    def stop(self):
        inst = self.instance
        if inst is not None and hasattr(inst, "_ray_tpu_isolated_close"):
            try:
                inst._ray_tpu_isolated_close()
            except Exception:
                pass
        failed = []
        with self._submit_lock:
            self._stopped.set()
            # Drain under the lock; COMPLETE outside it.  complete_error
            # fans out to owner callbacks and (for remote owners) RPCs —
            # running those while holding the submit lock would block
            # every concurrent submitter behind user-visible work.
            try:
                while True:
                    spec = self._queue.get_nowait()
                    if spec is not None:
                        failed.append(spec)
            except queue.Empty:
                pass
            for _ in self._threads:
                self._queue.put(None)
        for spec in failed:
            self._runtime.task_manager.complete_error(
                spec, self._dead_error(), allow_retry=False)
        # Drop this mailbox's depth series: gauges keyed by actor name
        # would otherwise accumulate one stale entry per dead actor
        # (serve replicas churn names every rolling update).
        try:
            from ..observability.metrics import overload_counters

            overload_counters()["queue_depth"].remove(
                tags={"queue": f"actor:{self.info.display_name()}"})
        except Exception:
            pass


class ActorInfo:
    def __init__(self, actor_id: ActorID, klass: type, init_args, init_kwargs,
                 *, name: str = "", namespace: str = "", max_restarts: int = 0,
                 max_task_retries: int = 0,
                 max_concurrency: Optional[int] = None,
                 max_pending_calls: int = -1, lifetime: Optional[str] = None,
                 resources: Optional[Dict[str, float]] = None,
                 isolate: bool = False):
        self.actor_id = actor_id
        self.klass = klass
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.name = name
        self.namespace = namespace
        self.max_restarts = max_restarts
        self.max_task_retries = max_task_retries
        self.max_pending_calls = max_pending_calls
        self.lifetime = lifetime
        self.resources = resources or {}
        self.isolate = isolate
        # Resource-accounting flags: acquire happens on a background
        # thread at creation; release must happen exactly once across
        # the kill / failed-creation / double-kill paths.
        self.resources_acquired = False
        self.resources_released = False
        self.state = ActorState.PENDING_CREATION
        self.num_restarts = 0
        # Coroutine *and* async-generator methods make an actor async
        # (iscoroutinefunction alone misses ``async def`` generators).
        def _is_async_callable(m):
            return (inspect.iscoroutinefunction(m)
                    or inspect.isasyncgenfunction(m))

        self.is_async = bool(inspect.getmembers(klass, _is_async_callable))
        # Async actors default to high concurrency (reference: actor.py —
        # asyncio actors use max_concurrency=1000 unless set explicitly);
        # sync actors default to 1 (ordered execution).
        if max_concurrency is None:
            max_concurrency = 1000 if self.is_async else 1
        self.max_concurrency = max_concurrency

    def display_name(self) -> str:
        return self.name or f"{self.klass.__name__}({self.actor_id.hex()[:8]})"


class ActorManager:
    """Registry of actors — the in-process stand-in for the GCS actor
    table (gcs_actor_manager.h)."""

    def __init__(self, runtime):
        self._runtime = runtime
        self._lock = threading.Lock()
        self._cores: Dict[ActorID, _ActorCore] = {}
        self._named: Dict[Tuple[str, str], ActorID] = {}

    def create(self, info: ActorInfo) -> _ActorCore:
        with self._lock:
            key = (info.namespace, info.name)
            if info.name:
                if key in self._named:
                    existing = self._cores.get(self._named[key])
                    if existing is not None and existing.info.state not in (
                            ActorState.DEAD,):
                        raise ValueError(
                            f"actor name {info.name!r} already taken in "
                            f"namespace {info.namespace!r}")
                self._named[key] = info.actor_id
            core = _ActorCore(self._runtime, info)
            self._cores[info.actor_id] = core
            return core

    def get_core(self, actor_id: ActorID) -> Optional[_ActorCore]:
        with self._lock:
            return self._cores.get(actor_id)

    def get_named(self, name: str, namespace: str) -> Optional[ActorID]:
        with self._lock:
            return self._named.get((namespace, name))

    def list_named(self, namespace: Optional[str] = None):
        with self._lock:
            return [
                {"name": n, "namespace": ns, "actor_id": aid.hex()}
                for (ns, n), aid in self._named.items()
                if namespace is None or ns == namespace
            ]

    def actor_name(self, actor_id: ActorID) -> str:
        core = self.get_core(actor_id)
        return core.info.name if core else ""

    def num_restarts(self, actor_id: ActorID) -> int:
        core = self.get_core(actor_id)
        return core.info.num_restarts if core else 0

    def get_handle(self, actor_id: ActorID):
        from .actor import ActorHandle

        core = self.get_core(actor_id)
        if core is None:
            raise ValueError(f"no such actor: {actor_id!r}")
        return ActorHandle(actor_id, core.info.klass, self._runtime)

    def kill(self, actor_id: ActorID, no_restart: bool = True):
        core = self.get_core(actor_id)
        if core is None:
            return
        info = core.info
        if (not no_restart and info.max_restarts != 0
                and (info.max_restarts < 0
                     or info.num_restarts < info.max_restarts)):
            # Restart: new core, re-run constructor (state is lost —
            # matches reference restart semantics).
            info.num_restarts += 1
            info.state = ActorState.RESTARTING
            # The restart is a load-bearing moment in any recovery
            # story — mark it in the (shipped) timeline so the merged
            # trace shows WHERE the gap in an actor's lane came from.
            try:
                from ..observability.timeline import (process_pid,
                                                      record_event)

                record_event(
                    "actor_restart", "i", pid=process_pid(),
                    tid=threading.current_thread().name,
                    args={"actor_id": actor_id.hex()[:16],
                          "name": info.display_name(),
                          "restarts_used": info.num_restarts})
            except Exception:
                pass
            core.stop()
            new_core = _ActorCore(self._runtime, info)
            with self._lock:
                self._cores[actor_id] = new_core
            self._runtime.submit_actor_creation_for_restart(new_core)
            return
        info.state = ActorState.DEAD
        core.stop()
        with self._lock:
            if info.name and self._named.get(
                    (info.namespace, info.name)) == actor_id:
                del self._named[(info.namespace, info.name)]

    def shutdown(self):
        with self._lock:
            cores = list(self._cores.values())
        for core in cores:
            core.info.state = ActorState.DEAD
            core.stop()

    def num_alive(self) -> int:
        with self._lock:
            return sum(1 for c in self._cores.values()
                       if c.info.state == ActorState.ALIVE)
