"""Cluster-mode attach point.

Reference semantics: ray.init(address=...) connects a driver to a
running cluster (worker.py:2256 connect()).  The multi-process cluster
runtime (head/GCS + per-node workers over sockets) is under active
construction; until it lands, attaching raises a clear error rather than
silently degrading to local mode.
"""

from __future__ import annotations


def connect_to_cluster(address: str, namespace: str = "",
                       runtime_env=None):
    raise NotImplementedError(
        f"cluster attach (address={address!r}) is not available yet in "
        f"this build — use ray_tpu.init() for the in-process runtime")
