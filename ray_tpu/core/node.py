"""Node bootstrap: head start, worker processes, cluster attach.

Reference analogues: python/ray/_private/node.py:1363
(start_head_processes), _private/services.py:1445/:1514 (spawning the
gcs_server / raylet binaries), and worker.py:2256 connect().

Process model: the *head* is a lightweight control-plane server
(ray_tpu.cluster.head.HeadServer) run either in-process (default, the
driver doubles as head node — matches ``ray.init()`` head mode) or as
its own subprocess.  *Worker nodes* are subprocesses running
``python -m ray_tpu.cluster.worker_main`` — each boots its own Runtime
+ NodeServer and registers with the head.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

_head_server = None
_head_lock = threading.Lock()


def start_head(host: str = "127.0.0.1", port: int = 0,
               storage_path: Optional[str] = None) -> str:
    """Start an in-process head server; returns its address.
    ``storage_path`` enables GCS fault tolerance (tables persist and
    replay on restart at the same address)."""
    global _head_server
    from ..cluster.head import HeadServer

    with _head_lock:
        if _head_server is None:
            _head_server = HeadServer(host, port,  # raylint: disable=blocking-under-lock -- heads started here are never standbys, so the construction-time seed/dial path the analysis sees is unreachable; the lock guards the singleton
                                      storage_path=storage_path)
        return _head_server.address


def stop_head():
    global _head_server
    with _head_lock:
        if _head_server is not None:
            _head_server.shutdown()
            _head_server = None


def connect_to_cluster(address: str, *, namespace: str = "",
                       runtime_env: Optional[dict] = None,
                       num_cpus: Optional[float] = None,
                       num_tpus: Optional[float] = None,
                       resources: Optional[Dict[str, float]] = None,
                       node_name: str = "",
                       labels: Optional[Dict[str, str]] = None):
    """Boot a local Runtime and attach it to a running head
    (reference: ray.init(address=...) → connect(), worker.py:2256)."""
    from . import runtime as runtime_mod

    if address == "auto":
        address = os.environ.get("RAY_TPU_HEAD_ADDRESS", "")
        if not address:
            raise ConnectionError(
                'init(address="auto") needs RAY_TPU_HEAD_ADDRESS set')
    rt = runtime_mod.init_runtime(
        num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
        namespace=namespace, runtime_env=runtime_env)
    if rt.cluster is None:
        rt.attach_cluster(address, node_name=node_name, labels=labels)
    return rt


def start_worker_process(head_address: str, *,
                         num_cpus: Optional[float] = None,
                         resources: Optional[Dict[str, float]] = None,
                         node_name: str = "",
                         labels: Optional[Dict[str, str]] = None,
                         env: Optional[Dict[str, str]] = None,
                         force_cpu_platform: bool = True
                         ) -> subprocess.Popen:
    """Spawn a worker-node subprocess (reference: services.py:1514
    start_raylet — here the "raylet" and the worker runtime share one
    process).  ``force_cpu_platform`` keeps worker jax off the TPU so
    the driver retains chip ownership (one jax TPU client per chip)."""
    cmd = [sys.executable, "-m", "ray_tpu.cluster.worker_main",
           "--head", head_address]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    if node_name:
        cmd += ["--name", node_name]
    if labels:
        cmd += ["--labels", json.dumps(labels)]
    child_env = dict(os.environ)
    if force_cpu_platform:
        child_env.setdefault("JAX_PLATFORMS", "cpu")
    # Worker prints must reach the node log promptly (and survive a
    # crash) — see worker_main's log capture.
    child_env.setdefault("PYTHONUNBUFFERED", "1")
    child_env.update(env or {})
    return subprocess.Popen(cmd, env=child_env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)


def wait_for_nodes(head_address: str, count: int,
                   timeout: float = 30.0) -> None:
    """Block until ``count`` nodes are alive at the head."""
    from ..cluster.rpc import RpcClient

    client = RpcClient(head_address)
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            nodes = client.call("list_nodes", {})
            if sum(1 for n in nodes if n["alive"]) >= count:
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"cluster did not reach {count} nodes in {timeout}s")
    finally:
        client.close()
