"""Local task scheduling: dependency resolution + resource-gated dispatch.

Reference semantics: the raylet's two-stage scheduler
(src/ray/raylet/scheduling/cluster_task_manager.h:42 picks a node;
local_task_manager.h dispatches locally once args are local and resources
are acquired, pulling workers from a pool).  In the in-process runtime
there is one node, so this collapses to: wait for ObjectRef args
(DependencyManager, dependency_manager.h:49) → acquire resources →
run on a worker thread.  Cluster mode swaps the dispatch backend for
worker processes (ray_tpu.core.node).
"""

from __future__ import annotations

import ctypes
import threading
from collections import deque
from enum import Enum
from typing import Callable, Dict, List, Optional, Set

from .ids import ObjectID, TaskID
from .object_ref import ObjectRef
from .resources import ResourceSet
from .task_spec import TaskSpec
from ..exceptions import TaskCancelledError


class TaskState(Enum):
    WAITING_DEPS = "WAITING_DEPS"
    QUEUED = "QUEUED"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    CANCELLED = "CANCELLED"


class _Entry:
    __slots__ = ("spec", "state", "pending_deps", "thread", "demand",
                 "cancelled")

    def __init__(self, spec: TaskSpec, demand: Dict[str, float]):
        self.spec = spec
        self.state = TaskState.WAITING_DEPS
        self.pending_deps = 0
        self.thread: Optional[threading.Thread] = None
        self.demand = demand
        self.cancelled = False


def collect_dependencies(spec: TaskSpec) -> List[ObjectRef]:
    """Top-level ObjectRef args only — nested refs are not awaited
    (matches reference: only direct arguments are resolved)."""
    deps = [a for a in spec.args if isinstance(a, ObjectRef)]
    deps += [v for v in spec.kwargs.values() if isinstance(v, ObjectRef)]
    return deps


class LocalScheduler:
    def __init__(self, resources: ResourceSet,
                 execute_fn: Callable[[TaskSpec], None],
                 on_cancelled: Callable[[TaskSpec], None],
                 object_store):
        self._resources = resources
        self._execute_fn = execute_fn
        self._on_cancelled = on_cancelled
        self._object_store = object_store
        self._lock = threading.Lock()
        self._entries: Dict[TaskID, _Entry] = {}
        self._ready: deque = deque()
        self._cond = threading.Condition(self._lock)
        self._shutdown = False
        self._children: Dict[TaskID, Set[TaskID]] = {}
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="raytpu-dispatch", daemon=True)
        self._dispatcher.start()

    # -- submission ----------------------------------------------------------
    def backlog(self) -> int:
        """Tasks queued but not yet running (resources not acquired) —
        the cluster dispatcher consults this: available-resource checks
        alone don't see a submission burst still sitting in the queue."""
        with self._lock:
            return len(self._ready)

    def submit(self, spec: TaskSpec):
        entry = _Entry(spec, dict(spec.resources))
        if not self._resources.can_ever_fit(entry.demand):
            raise ValueError(
                f"task {spec.repr_name()} demands {entry.demand}, which can "
                f"never be satisfied by node resources {self._resources.total}"
            )
        deps = collect_dependencies(spec)
        with self._lock:
            self._entries[spec.task_id] = entry
            if spec.parent_task_id is not None:
                self._children.setdefault(
                    spec.parent_task_id, set()).add(spec.task_id)
            entry.pending_deps = len(deps)
            if entry.pending_deps == 0:
                entry.state = TaskState.QUEUED
                self._ready.append(spec.task_id)  # raylint: disable=unbounded-mailbox -- resource-gated backlog, not demand-driven: admission happens upstream (cluster spill + deadline shed at dispatch drains expired entries)
                self._cond.notify_all()
        for dep in deps:
            self._object_store.add_done_callback(
                dep.object_id(), self._make_dep_callback(spec.task_id))

    def _make_dep_callback(self, task_id: TaskID):
        def cb(_obj):
            with self._lock:
                entry = self._entries.get(task_id)
                if entry is None or entry.state != TaskState.WAITING_DEPS:
                    return
                entry.pending_deps -= 1
                if entry.pending_deps <= 0:
                    entry.state = TaskState.QUEUED
                    self._ready.append(task_id)
                    self._cond.notify_all()

        return cb

    # -- dispatch ------------------------------------------------------------
    def _dispatch_loop(self):
        while True:
            with self._lock:
                self._cond.wait_for(
                    lambda: self._shutdown or len(self._ready) > 0)
                if self._shutdown:
                    return
                task_id = self._pop_fitting()
                if task_id is None:
                    # Nothing fits right now — wait for a resource release
                    # (release notifies via ResourceSet; poll on a timer).
                    self._cond.wait(0.01)
                    continue
                entry = self._entries[task_id]
                entry.state = TaskState.RUNNING
            thread = threading.Thread(
                target=self._run_entry, args=(entry,),
                name=f"raytpu-worker-{entry.spec.repr_name()[:32]}",
                daemon=True)
            entry.thread = thread
            thread.start()

    def _pop_fitting(self) -> Optional[TaskID]:
        """First queued task whose demand fits available resources."""
        for i, task_id in enumerate(self._ready):
            entry = self._entries.get(task_id)
            if entry is None or entry.state != TaskState.QUEUED:
                continue
            if self._resources.try_acquire(entry.demand):
                del self._ready[i]
                return task_id
        return None

    def _run_entry(self, entry: _Entry):
        try:
            if entry.cancelled:
                self._on_cancelled(entry.spec)
            else:
                self._execute_fn(entry.spec)
        finally:
            self._resources.release(entry.demand)
            with self._lock:
                entry.state = TaskState.FINISHED
                # A retry may have re-registered the same task_id with a
                # fresh entry — only remove if the table still points at us.
                if self._entries.get(entry.spec.task_id) is entry:
                    del self._entries[entry.spec.task_id]
                    self._children.pop(entry.spec.task_id, None)
                self._cond.notify_all()

    # -- cancellation --------------------------------------------------------
    def cancel(self, task_id: TaskID, force: bool = False,
               recursive: bool = False) -> bool:
        """Returns True if the task was found (pending or running)."""
        targets = [task_id]
        if recursive:
            with self._lock:
                stack = [task_id]
                while stack:
                    t = stack.pop()
                    kids = self._children.get(t, set())
                    targets.extend(kids)
                    stack.extend(kids)
        found = False
        for t in targets:
            found = self._cancel_one(t, force) or found
        return found

    def _cancel_one(self, task_id: TaskID, force: bool) -> bool:
        with self._lock:
            entry = self._entries.get(task_id)
            if entry is None:
                return False
            entry.cancelled = True
            if entry.state in (TaskState.WAITING_DEPS, TaskState.QUEUED):
                entry.state = TaskState.CANCELLED
                try:
                    self._ready.remove(task_id)
                except ValueError:
                    pass
                self._entries.pop(task_id, None)
                spec = entry.spec
                to_seal = spec
            else:
                to_seal = None
                thread = entry.thread
        if to_seal is not None:
            self._on_cancelled(to_seal)
            return True
        # Running: interrupt the worker thread (best-effort async raise —
        # the in-process analogue of the executor-interrupt RPC,
        # core_worker.h:955 CancelTask).
        if thread is not None and thread.is_alive():
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(thread.ident),
                ctypes.py_object(TaskCancelledError))
        return True

    def assigned_resources(self, task_id: TaskID) -> Dict[str, float]:
        with self._lock:
            entry = self._entries.get(task_id)
            return dict(entry.demand) if entry else {}

    def num_pending(self) -> int:
        with self._lock:
            return len(self._entries)

    def shutdown(self):
        with self._lock:
            self._shutdown = True
            self._cond.notify_all()
        self._dispatcher.join(timeout=2.0)
