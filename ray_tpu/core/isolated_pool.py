"""Pooled worker subprocesses: opt-in process isolation (N8) + memory
watermark OOM defense (N22).

Reference: src/ray/raylet/worker_pool.h:216 (prestarted process
workers, startup handshake, idle reaping) and
src/ray/raylet/worker_killing_policy.h:34 (when node memory crosses the
watermark, kill retriable tasks first, newest/largest first).

Design here: the node process executes tasks inline by default (the
TPU-native common case — everything shares one jax runtime), and
``@ray_tpu.remote(isolate=True)`` routes a task/actor into a pooled
subprocess so a crash (os._exit, segfault, unbounded allocation) takes
down only that worker.  A crashed worker surfaces as
``WorkerCrashedError`` / ``OutOfMemoryError`` — system failures, so the
task manager's normal retry budget re-runs the task on a fresh worker.

The memory monitor samples the node's available memory; past the
watermark it SIGKILLs the isolated worker with the largest RSS whose
task is retriable (policy above) — the node process and its actors
keep serving.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ..exceptions import OutOfMemoryError, WorkerCrashedError
from .config import GLOBAL_CONFIG


class _Child:
    """One pooled subprocess (worker_pool.h PopWorker unit)."""

    def __init__(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # the parent owns the TPU
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.isolated_worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=None, env=env)
        self.lock = threading.Lock()
        self.busy = False
        self.retriable = True     # current task's retry eligibility
        self.oom_killed = False
        self.last_used = time.monotonic()
        from .isolated_worker import read_frame

        try:
            hello = read_frame(self.proc.stdout)
        except (EOFError, OSError) as e:
            self.kill()
            raise WorkerCrashedError(
                f"isolated worker died during startup handshake") from e
        if hello.get("ready") != self.proc.pid:
            self.kill()
            raise WorkerCrashedError(
                f"isolated worker handshake failed: {hello!r}")

    def request(self, payload: Dict[str, Any]) -> Any:
        """Round-trip one op; raises WorkerCrashedError/OutOfMemoryError
        if the child dies mid-call.  Serialized per child: concurrent
        callers (isolated actor with max_concurrency > 1) would
        interleave frames on the one pipe pair."""
        from .isolated_worker import read_frame, write_frame

        try:
            with self.lock:
                write_frame(self.proc.stdin, payload)
                reply = read_frame(self.proc.stdout)
        except (EOFError, OSError, BrokenPipeError) as e:
            rc = self.proc.poll()
            if self.oom_killed:
                raise OutOfMemoryError(
                    f"isolated worker pid={self.proc.pid} killed by the "
                    f"memory monitor (node over watermark)") from e
            raise WorkerCrashedError(
                f"isolated worker pid={self.proc.pid} died "
                f"(exit code {rc}) during {payload.get('op')}") from e
        if "err" in reply:
            raise reply["err"]
        return reply["ok"]

    def rss_bytes(self) -> int:
        try:
            with open(f"/proc/{self.proc.pid}/statm") as f:
                return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
        except (OSError, IndexError, ValueError):
            return 0

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self, oom: bool = False):
        self.oom_killed = oom or self.oom_killed
        try:
            self.proc.kill()
        except Exception:
            pass

    def shutdown(self):
        from .isolated_worker import write_frame

        try:
            write_frame(self.proc.stdin, {"op": "exit"})
            self.proc.wait(timeout=2)
        except Exception:
            self.kill()


class IsolatedPool:
    """Process pool for isolate=True tasks + dedicated actor workers."""

    def __init__(self, node_memory_bytes: Optional[float] = None):
        self.max_workers = GLOBAL_CONFIG.isolated_pool_max_workers()
        self.idle_timeout_s = GLOBAL_CONFIG.isolated_pool_idle_timeout_s()
        self._idle: List[_Child] = []
        self._busy: List[_Child] = []
        self._dedicated: List[_Child] = []
        self._spawning = 0  # slots reserved by in-flight _Child() spawns
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._stopped = False
        for _ in range(GLOBAL_CONFIG.isolated_pool_prestart()):
            self._idle.append(_Child())
        self._reaper = threading.Thread(target=self._reap_loop,
                                        daemon=True,
                                        name="isolated-pool-reaper")
        self._reaper.start()
        self._monitor = _MemoryMonitor(self, node_memory_bytes)

    # ------------------------------------------------------------ tasks
    def run(self, fn, args, kwargs, retriable: bool = True) -> Any:
        """Execute ``fn`` in a pooled worker; blocks for a free slot."""
        child = self._acquire()
        child.retriable = retriable
        try:
            return child.request({"op": "task", "fn": fn,
                                  "args": args, "kwargs": kwargs})
        finally:
            self._release(child)

    def _acquire(self) -> _Child:
        with self._cv:
            while True:
                if self._stopped:
                    raise WorkerCrashedError("isolated pool shut down")
                while self._idle:
                    c = self._idle.pop()
                    if c.alive():
                        self._busy.append(c)
                        c.busy = True
                        return c
                    c.kill()
                if len(self._busy) + self._spawning < self.max_workers:
                    # Reserve the slot before dropping the lock, or a
                    # burst of acquirers all spawn past the cap.
                    self._spawning += 1
                    break
                self._cv.wait(timeout=1.0)
        try:
            c = _Child()
        finally:
            with self._cv:
                self._spawning -= 1
                self._cv.notify_all()
        with self._cv:
            self._busy.append(c)
            c.busy = True
        return c

    def _release(self, child: _Child):
        with self._cv:
            if child in self._busy:
                self._busy.remove(child)
            child.busy = False
            child.last_used = time.monotonic()
            if child.alive() and not self._stopped:
                self._idle.append(child)
            else:
                child.kill()
            self._cv.notify_all()

    # ------------------------------------------------------------ actors
    def spawn_dedicated(self) -> _Child:
        """A worker owned by one isolated actor for its lifetime (not
        reused; dies with the actor)."""
        c = _Child()
        with self._lock:
            self._dedicated.append(c)
        return c

    def drop_dedicated(self, child: _Child):
        with self._lock:
            if child in self._dedicated:
                self._dedicated.remove(child)
        child.shutdown()

    # ------------------------------------------------------------ monitor
    def _oom_candidates(self) -> List[_Child]:
        """Busy isolated workers, retriable-first then largest-RSS —
        worker_killing_policy.h ordering."""
        with self._lock:
            busy = list(self._busy) + [c for c in self._dedicated
                                       if c.alive()]
        return sorted(busy, key=lambda c: (not c.retriable,
                                           -c.rss_bytes()))

    def _reap_loop(self):
        prestart = GLOBAL_CONFIG.isolated_pool_prestart()
        while not self._stopped:
            time.sleep(1.0)
            now = time.monotonic()
            with self._lock:
                keep, reap = [], []
                for c in self._idle:
                    if (len(self._idle) - len(reap) > prestart
                            and now - c.last_used > self.idle_timeout_s):
                        reap.append(c)
                    else:
                        keep.append(c)
                self._idle = keep
            for c in reap:
                c.shutdown()

    def shutdown(self):
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._monitor.stop()
        self._reaper.join(timeout=2.0)
        with self._lock:
            everyone = self._idle + self._busy + self._dedicated
            self._idle, self._busy, self._dedicated = [], [], []
        for c in everyone:
            c.kill()


class IsolatedInstance:
    """Actor instance living in a dedicated worker subprocess; method
    lookups forward over the pipe.  Duck-types the real instance for
    ActorCore (``getattr(instance, method)(*args)``)."""

    def __init__(self, pool: IsolatedPool, klass: type, args, kwargs):
        self._pool = pool
        self._child = pool.spawn_dedicated()
        # Actors rank AFTER retriable tasks in the OOM-kill order —
        # losing actor state is worse than re-running a task
        # (worker_killing_policy.h: retriable first).
        self._child.retriable = False
        self._child.busy = True
        self._klass_name = klass.__name__
        try:
            self._child.request({"op": "init", "cls": klass,
                                 "args": args, "kwargs": kwargs})
        except BaseException:
            # Failed creation must not leak the live subprocess (a
            # restarting actor would leak one per attempt).
            pool.drop_dedicated(self._child)
            raise

    def __getattr__(self, name: str):
        # Dunder lookups (pickling, repr machinery) must fail fast;
        # single-underscore user methods forward like any other.
        if name.startswith("__"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            return self._child.request({"op": "call", "method": name,
                                        "args": args, "kwargs": kwargs})

        call.__name__ = name
        return call

    def _ray_tpu_isolated_close(self):
        self._pool.drop_dedicated(self._child)


class _MemoryMonitor:
    """Node watermark killer (memory_monitor.h:52 +
    worker_killing_policy.h:34): above the watermark, kill the best
    OOM candidate; isolated workers only — the node process itself is
    never touched."""

    def __init__(self, pool: IsolatedPool,
                 node_memory_bytes: Optional[float] = None):
        self.pool = pool
        self.watermark = GLOBAL_CONFIG.memory_usage_threshold()
        # Physical memory only: the watermark protects the BOX.  A
        # logical resources={"memory": ...} override is a scheduling
        # quota, not a measurement baseline — mixing them makes the
        # fraction nonsensical (node_memory_bytes is accepted for tests
        # that fake a box size).
        self.total = float(node_memory_bytes or _meminfo("MemTotal"))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="isolated-oom-monitor")
        self._thread.start()

    def _loop(self):
        interval = GLOBAL_CONFIG.memory_monitor_refresh_ms() / 1000.0
        if interval <= 0:
            return
        while not self._stop.wait(interval):
            try:
                used_frac = self._used_fraction()
                if used_frac < self.watermark:
                    continue
                for child in self.pool._oom_candidates():
                    child.kill(oom=True)
                    break
            except Exception:
                pass

    def _used_fraction(self) -> float:
        avail = _meminfo("MemAvailable")
        if not avail or not self.total:
            return 0.0
        return 1.0 - avail / self.total

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2.0)


def _meminfo(key: str) -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith(key + ":"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0
