"""TPU pod/slice topology detection → node placement labels.

Reference: python/ray/_private/accelerators/tpu.py:14-42 — Ray detects
the TPU pod environment from the metadata env vars the TPU runtime
injects (accelerator type, worker id, worker hostnames) and advertises
them so the autoscaler/scheduler can treat a pod slice as a gang unit
(pod command runners: autoscaler/_private/gcp/tpu_command_runner.py:1-6).

TPU-first reading: a *slice* is the ICI domain — collectives inside a
slice ride ICI, across slices they ride DCN.  The head's placement
strategies (cluster/head.py SLICE_PACK / SLICE_SPREAD) use these labels
to (a) pack one train gang onto the hosts of a single slice in
worker-index order (ICI-adjacent), and (b) spread pipeline stages one
slice each so only stage boundaries cross DCN.

Env contract (the TPU VM runtime sets these; tests set them manually):
- ``TPU_ACCELERATOR_TYPE``  e.g. "v5litepod-16"
- ``TPU_WORKER_ID``         this host's index within its slice
- ``TPU_WORKER_HOSTNAMES``  comma-separated hosts of the slice
- ``MEGASCALE_SLICE_ID``    slice index in a multislice deployment
- ``TPU_NAME``              slice/queued-resource name
``RAY_TPU_SLICE`` / ``RAY_TPU_WORKER_INDEX`` override for tests.
"""

from __future__ import annotations

import os
from typing import Dict

# Label keys (reference uses "ray.io/..." style node labels).
SLICE_LABEL = "ray_tpu.io/slice"
WORKER_INDEX_LABEL = "ray_tpu.io/worker-index"
ACCELERATOR_TYPE_LABEL = "ray_tpu.io/accelerator-type"
SLICE_HOSTS_LABEL = "ray_tpu.io/slice-host-count"


def detect_topology_labels(env: Dict[str, str] = None) -> Dict[str, str]:
    """Labels describing this host's position in the TPU topology.

    Empty dict off-TPU (no env markers).  A multislice deployment gets
    ``slice = <name>/<MEGASCALE_SLICE_ID>`` so slices of one queued
    resource stay distinct.
    """
    e = os.environ if env is None else env
    labels: Dict[str, str] = {}

    slice_name = e.get("RAY_TPU_SLICE")
    if slice_name is None:
        base = e.get("TPU_NAME") or ""
        mega = e.get("MEGASCALE_SLICE_ID")
        if mega is not None:
            slice_name = f"{base or 'slice'}/{mega}"
        elif base:
            slice_name = base
        elif e.get("TPU_ACCELERATOR_TYPE"):
            # Single unnamed slice: all its hosts share the hostname
            # list, so the list itself identifies the slice.
            slice_name = e.get("TPU_WORKER_HOSTNAMES", "slice")
    if slice_name:
        labels[SLICE_LABEL] = slice_name

    widx = e.get("RAY_TPU_WORKER_INDEX", e.get("TPU_WORKER_ID"))
    if widx is not None:
        labels[WORKER_INDEX_LABEL] = str(widx)

    acc = e.get("TPU_ACCELERATOR_TYPE")
    if acc:
        labels[ACCELERATOR_TYPE_LABEL] = acc

    hosts = e.get("TPU_WORKER_HOSTNAMES")
    if hosts:
        labels[SLICE_HOSTS_LABEL] = str(len(hosts.split(",")))

    return labels
