"""Per-thread execution context (who am I, which task am I running).

Reference semantics: python/ray/runtime_context.py:15 — introspection of
current job/task/actor/node plus ``was_current_actor_reconstructed`` etc.
"""

from __future__ import annotations

import contextvars
from typing import Any, Dict, Optional

from .ids import ActorID, JobID, NodeID, TaskID, WorkerID

# Per-asyncio-task, not merely per-thread (see core/deadlines.py):
# an async actor interleaves requests on one loop thread, and the
# executing task's identity must follow each request across awaits
# — log records and nested submissions stamp from here.
_ctx_var: "contextvars.ContextVar[Optional[TaskContext]]" = \
    contextvars.ContextVar("ray_tpu_task_ctx", default=None)


class TaskContext:
    __slots__ = ("task_id", "task_name", "actor_id", "attempt_number",
                 "parent_task_id", "trace_id", "span_id", "deadline")

    def __init__(self, task_id: TaskID, task_name: str = "",
                 actor_id: Optional[ActorID] = None, attempt_number: int = 0,
                 parent_task_id: Optional[TaskID] = None,
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 deadline: Optional[float] = None):
        self.task_id = task_id
        self.task_name = task_name
        self.actor_id = actor_id
        self.attempt_number = attempt_number
        self.parent_task_id = parent_task_id
        # Distributed tracing (observability/tracing.py): the trace
        # this execution belongs to and the span it records.
        self.trace_id = trace_id
        self.span_id = span_id
        # Absolute end-to-end deadline (core/deadlines.py): user code
        # can read its remaining budget; batch flush drops entries
        # whose deadline passed while they coalesced.
        self.deadline = deadline


def set_task_context(ctx: Optional[TaskContext]):
    _ctx_var.set(ctx)


def current_task_context() -> Optional[TaskContext]:
    return _ctx_var.get()


class RuntimeContext:
    def __init__(self, runtime):
        self._runtime = runtime

    def get_job_id(self) -> str:
        return self._runtime.job_id.hex()

    def get_node_id(self) -> str:
        return self._runtime.node_id.hex()

    def get_worker_id(self) -> str:
        return self._runtime.worker_id.hex()

    def get_task_id(self) -> Optional[str]:
        ctx = current_task_context()
        return ctx.task_id.hex() if ctx else None

    def get_task_name(self) -> Optional[str]:
        ctx = current_task_context()
        return ctx.task_name if ctx else None

    def get_actor_id(self) -> Optional[str]:
        ctx = current_task_context()
        if ctx and ctx.actor_id is not None:
            return ctx.actor_id.hex()
        return None

    def get_actor_name(self) -> Optional[str]:
        aid = self.get_actor_id()
        if aid is None:
            return None
        return self._runtime.actor_manager.actor_name(ActorID.from_hex(aid))

    def get_attempt_number(self) -> int:
        ctx = current_task_context()
        return ctx.attempt_number if ctx else 0

    def get_trace_id(self) -> Optional[str]:
        """The distributed trace id of the current task (or the active
        driver-side tracing scope), for log correlation."""
        ctx = current_task_context()
        if ctx is not None and ctx.trace_id is not None:
            return ctx.trace_id
        from ..observability import tracing

        cur = tracing.current()
        return cur[0] if cur else None

    def get_deadline(self):
        """The current task's absolute end-to-end deadline (epoch s),
        or None when the request carries no deadline.  The ambient
        contextvar is consulted FIRST: it is per-asyncio-task, so it
        stays correct when an async actor interleaves many requests on
        one loop thread — the thread-local TaskContext is overwritten
        at every task switch and is only the sync-path fallback."""
        from . import deadlines

        ambient = deadlines.current()
        if ambient is not None:
            return ambient
        ctx = current_task_context()
        if ctx is not None and ctx.deadline is not None:
            return ctx.deadline
        return None

    def remaining_deadline_s(self):
        """Seconds of budget left (may be negative), or None."""
        from . import deadlines

        return deadlines.remaining(self.get_deadline())

    def current_actor(self):
        aid = self.get_actor_id()
        if aid is None:
            raise RuntimeError("not running inside an actor")
        return self._runtime.actor_manager.get_handle(ActorID.from_hex(aid))

    @property
    def namespace(self) -> str:
        return self._runtime.namespace

    def get_runtime_env(self) -> Dict[str, Any]:
        return dict(self._runtime.runtime_env or {})

    def get_assigned_resources(self) -> Dict[str, float]:
        ctx = current_task_context()
        if ctx is None:
            return {}
        return self._runtime.scheduler.assigned_resources(ctx.task_id)

    def was_current_actor_reconstructed(self) -> bool:
        aid = self.get_actor_id()
        if aid is None:
            return False
        return self._runtime.actor_manager.num_restarts(
            ActorID.from_hex(aid)) > 0
