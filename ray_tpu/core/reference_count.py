"""Distributed reference counting for object GC.

Reference semantics: src/ray/core_worker/reference_count.h:64 — every
object has an owner; the owner tracks (a) local Python references,
(b) submitted-task references (the object is an argument of a pending
task), (c) borrowers.  When all counts reach zero the value is freed;
if lineage pinning is on, the creating task's spec is retained until the
object itself goes out of scope so lost objects can be reconstructed.

This implementation is process-local (single-controller runtime); the
borrower half of the protocol becomes relevant in cluster mode where it
rides the pubsub channel (WaitForRefRemoved analogue).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

from .ids import ObjectID, TaskID


class _Ref:
    # Lineage pinning itself lives in TaskManager._lineage_refcount;
    # this table only counts references.
    __slots__ = ("local_refs", "submitted_task_refs", "borrowers")

    def __init__(self):
        self.local_refs = 0
        self.submitted_task_refs = 0
        # Remote nodes (by object-service address) holding fetched
        # copies (reference borrower protocol, reference_count.h:64).
        # A COUNT per address, not a set: releases are async and
        # unordered, so release-then-refetch must net to one hold
        # regardless of arrival order (set semantics has an ABA race
        # where a stale release cancels a fresh borrow).
        self.borrowers: Dict[str, int] = {}

    def total(self) -> int:
        return (self.local_refs + self.submitted_task_refs
                + sum(self.borrowers.values()))


class ReferenceCounter:
    def __init__(self, on_object_out_of_scope: Callable[[ObjectID], None]):
        self._lock = threading.RLock()
        self._refs: Dict[ObjectID, _Ref] = {}
        self._on_out_of_scope = on_object_out_of_scope
        self._out_of_scope_listeners: Dict[ObjectID, list] = {}

    def add_owned_object(self, object_id: ObjectID):
        with self._lock:
            self._refs.setdefault(object_id, _Ref())

    def add_local_reference(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            ref.local_refs += 1

    def remove_local_reference(self, object_id: ObjectID):
        self._decrement(object_id, "local_refs")

    def add_submitted_task_references(self, object_ids):
        with self._lock:
            for oid in object_ids:
                ref = self._refs.setdefault(oid, _Ref())
                ref.submitted_task_refs += 1

    def remove_submitted_task_references(self, object_ids):
        for oid in object_ids:
            self._decrement(oid, "submitted_task_refs")

    def add_borrower(self, object_id: ObjectID, borrower: str):
        """An owner-side hold for a remote node that fetched a copy;
        the value stays alive until every borrower releases."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return False  # already out of scope: borrow refused
            ref.borrowers[borrower] = ref.borrowers.get(borrower, 0) + 1
            return True

    def remove_borrower(self, object_id: ObjectID, borrower: str):
        to_free: Optional[ObjectID] = None
        listeners = []
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            n = ref.borrowers.get(borrower, 0) - 1
            if n > 0:
                ref.borrowers[borrower] = n
            else:
                ref.borrowers.pop(borrower, None)
            if ref.total() == 0:
                del self._refs[object_id]
                to_free = object_id
                listeners = self._out_of_scope_listeners.pop(object_id, [])
        self._fire(to_free, listeners)

    def remove_borrower_node(self, borrower: str):
        """Drop every hold a (dead) borrower node had — without this,
        objects it fetched stay pinned at their owners forever."""
        to_free = []
        with self._lock:
            for oid, ref in list(self._refs.items()):
                if (ref.borrowers.pop(borrower, None) is not None
                        and ref.total() == 0):
                    del self._refs[oid]
                    to_free.append(
                        (oid,
                         self._out_of_scope_listeners.pop(oid, [])))
        for oid, listeners in to_free:
            self._fire(oid, listeners)

    def _decrement(self, object_id: ObjectID, field: str):
        to_free: Optional[ObjectID] = None
        listeners = []
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            setattr(ref, field, max(0, getattr(ref, field) - 1))
            if ref.total() == 0:
                del self._refs[object_id]
                to_free = object_id
                listeners = self._out_of_scope_listeners.pop(object_id, [])
        self._fire(to_free, listeners)

    def _fire(self, to_free: Optional[ObjectID], listeners):
        if to_free is not None:
            self._on_out_of_scope(to_free)
            for cb in listeners:
                cb(to_free)

    def forget_if_unreferenced(self, object_id: ObjectID):
        """Drop a zero-count owned entry without firing the
        out-of-scope hook (used to back out never-submitted tasks whose
        return refs were never handed to anyone)."""
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None and ref.total() == 0:
                del self._refs[object_id]
                self._out_of_scope_listeners.pop(object_id, None)

    def has_reference(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._refs

    def local_ref_count(self, object_id: ObjectID) -> int:
        with self._lock:
            ref = self._refs.get(object_id)
            return 0 if ref is None else ref.local_refs

    def on_out_of_scope(self, object_id: ObjectID, callback):
        """Register a callback fired when the object leaves scope
        (lineage release hook — task_manager.h:240 analogue)."""
        with self._lock:
            if object_id in self._refs:
                self._out_of_scope_listeners.setdefault(object_id, []).append(
                    callback)
                return
        callback(object_id)

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)
