"""Node-local object plane: primary copies, spill to disk, chunk serving.

Reference semantics:
- Plasma store (src/ray/object_manager/plasma/store.h:55,
  object_lifecycle_manager.h): a per-node store of sealed immutable
  objects.  *Primary* copies are pinned — the owner's reference keeps
  them alive until an explicit free (free_primary RPC) — mirroring the
  raylet pinning the primary copy while the owner holds a reference.
- Spill/restore (src/ray/raylet/local_object_manager.h:41): above a
  capacity watermark (``object_store_memory_bytes`` ×
  ``object_spilling_threshold``), least-recently-used entries are
  written to disk in their flat wire layout and dropped from memory;
  reads restore them transparently, and remote chunk reads are served
  straight from the file without rehydrating.
- Chunk serving (object_manager.h:117, object_buffer_pool.h): remote
  pulls address fixed-size chunks over the object's flat wire layout
  (cluster.serialization.wire_layout).

TPU-first note: values are stored as ``Serialized`` (payload bytes +
live array externs).  Same-process consumers share the arrays at zero
cost; building the wire layout is zero-copy for host numpy externs and
pays exactly one device→host transfer for ``jax.Array`` externs, cached
for the lifetime of the entry.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from .config import GLOBAL_CONFIG
from .ids import ObjectID


class _Entry:
    __slots__ = ("sealed", "meta", "bufs", "size", "spill_path",
                 "last_access", "primary", "shm_path", "_mm")

    def __init__(self, sealed, size: int, primary: bool):
        self.sealed = sealed
        self.meta = None            # flat-layout meta (built lazily)
        self.bufs = None            # List[memoryview] over live arrays
        self.size = size
        self.spill_path: Optional[str] = None
        self.last_access = time.monotonic()
        self.primary = primary
        # Shared-memory backing (plasma proper, store.h:55): primary
        # copies live as flat layouts in a /dev/shm file; same-host
        # pullers mmap it instead of copying bytes over loopback.
        self.shm_path: Optional[str] = None
        self._mm = None


_FOREIGN_IDLE_S = 120.0  # serving-cache entries swept after this idle time


class LocalObjectStore:
    """Thread-safe sealed-object table with pinning, spill, and chunked
    reads.  One per node process."""

    def __init__(self, spill_dir: Optional[str] = None):
        self._lock = threading.RLock()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._mem_bytes = 0
        self._spill_dir = spill_dir
        self._spilled_bytes = 0
        self._shm_bytes = 0
        self._num_spilled = 0
        self._num_restored = 0

    # ----------------------------------------------------------- config
    def _capacity(self) -> int:
        return int(GLOBAL_CONFIG.object_store_memory_bytes())

    def _watermark(self) -> float:
        return (self._capacity()
                * float(GLOBAL_CONFIG.object_spilling_threshold()))

    def _spill_path(self) -> str:
        if self._spill_dir is None:
            configured = GLOBAL_CONFIG.object_spilling_directory()
            self._spill_dir = configured or tempfile.mkdtemp(
                prefix="ray_tpu_spill_")
            os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir

    # ------------------------------------------------------------ write
    def put_primary(self, oid: ObjectID, sealed) -> None:
        """Pin a primary copy on this node.  The entry stays (in memory
        or spilled) until ``free`` — the owner's out-of-scope hook.

        Big values are re-homed into SHARED MEMORY (store.h:55 — the
        plasma design proper): the flat wire layout is written to a
        /dev/shm file once at seal time, the entry's arrays become
        zero-copy views into the mapping, and a same-host puller mmaps
        the file instead of copying a gigabyte over loopback TCP.
        Same-node consumers see numpy views (a device array extern pays
        its device→host transfer here, where the copy already happens
        for serving)."""
        with self._lock:
            if oid in self._entries:
                return  # immutable: double-seal keeps the first copy
        entry = _Entry(sealed, sealed.size_bytes, primary=True)
        shm = None
        if (sealed.size_bytes
                >= int(GLOBAL_CONFIG.object_shm_min_bytes()) > 0):
            # Copy into tmpfs OUTSIDE the lock (gigabyte memcpy).
            shm = self._build_shm(oid, sealed)
        with self._lock:
            if oid in self._entries:
                # Lost a double-seal race after the copy: drop our file.
                if shm is not None:
                    self._discard_shm(shm)
                return
            if shm is not None:
                self._commit_shm_locked(entry, shm)
                # shm entries live on the tmpfs budget, not the store's
                # heap watermark — counting them would permanently
                # saturate it and spill every non-shm object on sight.
                self._shm_bytes += entry.size
            else:
                self._mem_bytes += entry.size
            self._entries[oid] = entry
            self._maybe_spill(exclude=oid)

    def _build_shm(self, oid: ObjectID, sealed):
        """Write ``sealed``'s flat layout into a /dev/shm file; returns
        (path, mm, meta) or None on failure (tiny container tmpfs)."""
        import mmap

        from ..cluster.serialization import wire_layout, wire_size

        shm_dir = GLOBAL_CONFIG.object_shm_directory()
        if not shm_dir or not os.path.isdir(shm_dir):
            return None
        path = os.path.join(
            shm_dir, f"ray_tpu-{os.getpid()}-{oid.hex()[:24]}")
        try:
            meta, bufs = wire_layout(sealed)
            total = wire_size(meta)
            with open(path, "wb+") as f:
                # Sequential write(), NOT fallocate + mmap fill: write()
                # lands user bytes straight into fresh tmpfs pages (one
                # pass of page traffic), where fallocate zero-commits
                # every page first and the memcpy re-dirties it — 3x
                # slower measured at 256 MB.  Running out of /dev/shm
                # mid-copy stays a catchable ENOSPC (write reserves as
                # it goes), never the SIGBUS a sparse truncate+store
                # would be.
                for b in bufs:
                    f.write(b)
                f.flush()
                mm = mmap.mmap(f.fileno(), total)
            return (path, mm, meta)
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass
            return None

    @staticmethod
    def _discard_shm(shm) -> None:
        path, mm, _meta = shm
        try:
            mm.close()
        except (OSError, BufferError):
            pass
        try:
            os.unlink(path)
        except OSError:
            pass

    @staticmethod
    def _commit_shm_locked(entry: _Entry, shm) -> None:
        """Swap the entry onto its shm backing — a handful of reference
        assignments, safe under the lock."""
        from ..cluster.serialization import sealed_from_flat

        path, mm, meta = shm
        mv = memoryview(mm)
        entry.sealed = sealed_from_flat(meta, mv.toreadonly())
        entry.meta = meta
        entry.bufs = [mv]
        entry.shm_path = path
        entry._mm = mm

    def serve_foreign(self, oid: ObjectID, sealed) -> dict:
        """Cache a *non-primary* sealed value (e.g. the owner's own
        memory-store copy) for chunk serving; returns its wire meta.
        Foreign entries are dropped (not spilled) under pressure and
        swept when idle — the real value lives elsewhere."""
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                entry = _Entry(sealed, sealed.size_bytes, primary=False)
                self._entries[oid] = entry
                self._mem_bytes += sealed.size_bytes
                self._maybe_spill(exclude=oid)
            return self._wire_meta_locked(oid, entry)

    # ------------------------------------------------------------- read
    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._entries

    def get_sealed(self, oid: ObjectID):
        """The sealed value, restoring from disk if spilled."""
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                return None
            entry.last_access = time.monotonic()
            if entry.sealed is None:
                self._restore_locked(oid, entry)
            return entry.sealed

    def wire_meta(self, oid: ObjectID) -> Optional[dict]:
        """{"meta": layout_meta, "size": total_bytes} for chunk pulls."""
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                return None
            entry.last_access = time.monotonic()
            return self._wire_meta_locked(oid, entry)

    def _wire_meta_locked(self, oid: ObjectID, entry: _Entry) -> dict:
        from ..cluster.serialization import wire_layout, wire_size

        if entry.meta is None or (entry.bufs is None
                                  and entry.sealed is not None):
            if entry.sealed is None:
                raise RuntimeError(f"{oid!r} spilled without meta")
            entry.meta, entry.bufs = wire_layout(entry.sealed)
        self._sweep_foreign_locked()
        return {"meta": entry.meta,
                "size": wire_size(entry.meta)}

    def read_chunk(self, oid: ObjectID, offset: int,
                   length: int) -> Optional[bytes]:
        """Serve ``length`` bytes of the flat layout.  Spilled entries
        are read from the file (no rehydration)."""
        from ..cluster.serialization import read_layout_chunk

        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                return None
            entry.last_access = time.monotonic()
            if entry.spill_path is not None and entry.sealed is None:
                path = entry.spill_path
            else:
                if entry.bufs is None:
                    self._wire_meta_locked(oid, entry)
                return read_layout_chunk(entry.bufs, offset, length)
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                return f.read(length)
        except OSError:
            # Restored (file unlinked) between the lock release and the
            # open: serve from memory on a second pass.
            with self._lock:
                entry = self._entries.get(oid)
                if entry is None:
                    return None
                if entry.sealed is None:
                    self._restore_locked(oid, entry)
                if entry.bufs is None:
                    self._wire_meta_locked(oid, entry)
                return read_layout_chunk(entry.bufs, offset, length)

    def ensure_shm(self, oid: ObjectID) -> Optional[str]:
        """Re-home an existing entry (primary or foreign) to shared
        memory if it qualifies; returns the shm path if backed.  The
        tmpfs copy happens outside the lock (a gigabyte memcpy under it
        would stall every concurrent chunk read)."""
        with self._lock:
            entry = self._entries.get(oid)
            if entry is None or entry.sealed is None:
                return None
            qualifies = (entry.size
                         >= int(GLOBAL_CONFIG.object_shm_min_bytes()) > 0)
            if entry.shm_path is not None or not qualifies:
                return entry.shm_path
            sealed = entry.sealed
        shm = self._build_shm(oid, sealed)
        with self._lock:
            cur = self._entries.get(oid)
            if cur is None or cur.sealed is None:
                if shm is not None:
                    self._discard_shm(shm)
                return None
            if cur.shm_path is None and shm is not None:
                self._commit_shm_locked(cur, shm)
                # Move the bytes from the heap budget to the shm one.
                self._mem_bytes -= cur.size
                self._shm_bytes += cur.size
            elif shm is not None and cur.shm_path != shm[0]:
                self._discard_shm(shm)
            return cur.shm_path

    def shm_path_of(self, oid: ObjectID) -> Optional[str]:
        """The /dev/shm backing file, if this entry was re-homed —
        same-host pullers mmap it instead of pulling bytes."""
        with self._lock:
            entry = self._entries.get(oid)
            return entry.shm_path if entry is not None else None

    def read_chunk_pieces(self, oid: ObjectID, offset: int, length: int):
        """Zero-copy memoryview pieces of the flat layout for the raw
        object stream (cluster/client.py ObjectStreamServer) — sendmsg
        ships them without assembling a bytes copy.  Spilled entries
        fall back to one file-read piece."""
        from ..cluster.serialization import read_layout_pieces

        with self._lock:
            entry = self._entries.get(oid)
            if entry is None:
                return None
            entry.last_access = time.monotonic()
            if not (entry.spill_path is not None and entry.sealed is None):
                if entry.bufs is None:
                    self._wire_meta_locked(oid, entry)
                return read_layout_pieces(entry.bufs, offset, length)
        data = self.read_chunk(oid, offset, length)
        return None if data is None else [memoryview(data)]

    # ------------------------------------------------------------- free
    def free(self, oid: ObjectID) -> None:
        with self._lock:
            entry = self._entries.pop(oid, None)
            if entry is None:
                return
            if entry.sealed is not None:
                if entry.shm_path is not None:
                    self._shm_bytes -= entry.size
                else:
                    self._mem_bytes -= entry.size
            if entry.spill_path is not None:
                self._spilled_bytes -= entry.size
                try:
                    os.unlink(entry.spill_path)
                except OSError:
                    pass
            if entry.shm_path is not None:
                # Unlink only: pullers holding the mapping keep the
                # pages alive (POSIX); fresh pulls fall back to TCP.
                try:
                    os.unlink(entry.shm_path)
                except OSError:
                    pass

    # ---------------------------------------------------------- spilling
    def _maybe_spill(self, exclude: Optional[ObjectID] = None) -> None:
        """Called under the lock after a write.  Spill (primaries) or
        drop (foreign) LRU entries until under the watermark."""
        watermark = self._watermark()
        if self._mem_bytes <= watermark:
            return
        candidates = sorted(
            ((oid, e) for oid, e in self._entries.items()
             if e.sealed is not None and oid != exclude
             # shm-backed entries are exempt: mappings may be shared
             # with same-host pullers, and tmpfs pages are already the
             # OS's to reclaim via swap.
             and e.shm_path is None),
            key=lambda kv: kv[1].last_access)
        for oid, entry in candidates:
            if self._mem_bytes <= watermark:
                break
            if entry.primary:
                self._spill_one_locked(oid, entry)
            else:
                self._entries.pop(oid, None)
                self._mem_bytes -= entry.size

    def _spill_one_locked(self, oid: ObjectID, entry: _Entry) -> None:
        from ..cluster.serialization import wire_layout

        if entry.meta is None or entry.bufs is None:
            entry.meta, entry.bufs = wire_layout(entry.sealed)
        path = os.path.join(self._spill_path(),
                            f"{oid.hex()}.obj")
        with open(path, "wb") as f:
            for b in entry.bufs:
                f.write(b)
        entry.spill_path = path
        entry.sealed = None
        entry.bufs = None
        self._mem_bytes -= entry.size
        self._spilled_bytes += entry.size
        self._num_spilled += 1

    def _restore_locked(self, oid: ObjectID, entry: _Entry) -> None:
        from ..cluster.serialization import sealed_from_flat

        with open(entry.spill_path, "rb") as f:
            raw = f.read()
        entry.sealed = sealed_from_flat(entry.meta, raw)
        entry.bufs = None  # rebuilt lazily over the restored arrays
        try:
            os.unlink(entry.spill_path)
        except OSError:
            pass
        entry.spill_path = None
        self._spilled_bytes -= entry.size
        self._mem_bytes += entry.size
        self._num_restored += 1
        self._maybe_spill(exclude=oid)

    def _sweep_foreign_locked(self) -> None:
        cutoff = time.monotonic() - _FOREIGN_IDLE_S
        stale = [oid for oid, e in self._entries.items()
                 if not e.primary and e.last_access < cutoff]
        for oid in stale:
            entry = self._entries.pop(oid)
            if entry.sealed is not None:
                self._mem_bytes -= entry.size

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "num_objects": len(self._entries),
                "mem_bytes": self._mem_bytes,
                "spilled_bytes": self._spilled_bytes,
                "shm_bytes": self._shm_bytes,
                "num_spilled": self._num_spilled,
                "num_restored": self._num_restored,
            }

    def destroy(self) -> None:
        with self._lock:
            paths = [e.spill_path for e in self._entries.values()
                     if e.spill_path]
            paths += [e.shm_path for e in self._entries.values()
                      if e.shm_path]
            self._entries.clear()
            self._mem_bytes = 0
            self._spilled_bytes = 0
            self._shm_bytes = 0
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
