"""Streaming-generator bookkeeping.

Reference semantics: SURVEY.md A.9 — tasks with ``num_returns="streaming"``
return an ObjectRefGenerator; each yielded item is reported out-of-band to
the owner (task_manager.h:301 HandleReportGeneratorItemReturns), tolerant
of out-of-order arrival; consumers block until the next index is reported
or the stream is finished.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .ids import ObjectID


class _Stream:
    def __init__(self):
        self.items: List[ObjectID] = []
        self.finished = False
        self.error_index: Optional[int] = None


class StreamingGeneratorManager:
    def __init__(self):
        self._streams: Dict[ObjectID, _Stream] = {}
        self._cond = threading.Condition()

    def create_stream(self, generator_id: ObjectID):
        with self._cond:
            self._streams[generator_id] = _Stream()

    def report_item(self, generator_id: ObjectID, item_id: ObjectID):
        with self._cond:
            stream = self._streams[generator_id]
            stream.items.append(item_id)
            self._cond.notify_all()

    def finish(self, generator_id: ObjectID):
        with self._cond:
            stream = self._streams.get(generator_id)
            if stream is not None:
                stream.finished = True
            self._cond.notify_all()

    def wait_item(self, generator_id: ObjectID, index: int,
                  timeout: Optional[float] = None) -> Optional[ObjectID]:
        """Block until item ``index`` exists; None = stream ended first."""
        with self._cond:
            stream = self._streams.get(generator_id)
            if stream is None:
                return None
            ok = self._cond.wait_for(
                lambda: len(stream.items) > index or stream.finished, timeout)
            if not ok:
                raise TimeoutError("streaming generator item wait timed out")
            if len(stream.items) > index:
                return stream.items[index]
            return None

    def num_items(self, generator_id: ObjectID) -> int:
        """Items reported so far (gates retry of a remote stream: a
        partially-consumed stream must not re-run)."""
        with self._cond:
            stream = self._streams.get(generator_id)
            return 0 if stream is None else len(stream.items)

    def is_finished(self, generator_id: ObjectID) -> bool:
        """True once the executor has reported the end of the stream."""
        with self._cond:
            stream = self._streams.get(generator_id)
            return stream is None or stream.finished

    def drop_stream(self, generator_id: ObjectID):
        with self._cond:
            self._streams.pop(generator_id, None)
