"""Owner-side task bookkeeping: pending table, retries, lineage.

Reference semantics: src/ray/core_worker/task_manager.h:212 — the owner
keeps every submitted task's spec until its returns are sealed; on
failure it resubmits up to ``max_retries``; specs of *finished* tasks are
retained ("lineage pinning", task_manager.h:219-240) while any of their
return objects are still in scope, so a lost object can be recomputed by
re-running its creating task (object_recovery_manager.h:41).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

from .ids import ObjectID, TaskID
from .object_store import RayObject
from .task_spec import TaskSpec, STREAMING
from ..exceptions import TaskCancelledError, TaskError


class TaskManager:
    def __init__(self, runtime):
        self._runtime = runtime
        self._lock = threading.Lock()
        self._pending: Dict[TaskID, TaskSpec] = {}
        self._lineage: Dict[TaskID, TaskSpec] = {}
        self._lineage_refcount: Dict[TaskID, int] = {}
        # Return oids with a registered out-of-scope listener: a spec
        # can finish more than once (lineage reconstruction re-runs it)
        # but each return must decrement the lineage refcount once.
        self._listening: Set[ObjectID] = set()
        self._num_retries: int = 0
        self._num_reconstructions: int = 0

    # -- lifecycle -----------------------------------------------------------
    def register_pending(self, spec: TaskSpec):
        with self._lock:
            self._pending[spec.task_id] = spec
        for oid in spec.return_ids:
            self._runtime.reference_counter.add_owned_object(oid)

    def complete_success(self, spec: TaskSpec, result):
        """Seal return objects from the task's result value."""
        store = self._runtime.object_store
        n = spec.num_returns
        if n == STREAMING:
            # Items were already sealed by the executor as they were
            # yielded; nothing left to do but drop from pending.
            pass
        elif n == 1:
            store.put(spec.return_ids[0],
                      RayObject(value=result))
        elif n == 0:
            pass
        else:
            values = list(result)
            if len(values) != n:
                err = TaskError(
                    spec.repr_name(),
                    ValueError(f"expected {n} return values, got "
                               f"{len(values)}"))
                self.complete_error(spec, err, allow_retry=False)
                return
            for oid, v in zip(spec.return_ids, values):
                store.put(oid, RayObject(value=v))
        self._finish(spec)

    def complete_remote(self, spec: TaskSpec, entries):
        """Seal return objects from a remote executor's reply.  Each
        entry is ``("inline", wire_bytes)`` — small results ride the
        reply, sealed here without re-serializing — or
        ``("stored", node_id, address, size)`` — the primary copy stays
        pinned on the executing node and the owner seals a location
        record (reference: small returns inline in the PushTask reply
        vs plasma-resident big returns, task_manager.cc seal paths +
        ownership-based directory)."""
        from ..cluster.serialization import from_wire

        store = self._runtime.object_store
        for oid, entry in zip(spec.return_ids, entries):
            if entry[0] == "inline":
                store.put(oid, RayObject(sealed=from_wire(entry[1])))
            else:
                _kind, node_id, address, size = entry
                store.put(oid, RayObject(location=(node_id, address),
                                         size_bytes=size))
                self._runtime.register_object_location(
                    oid, node_id, address)
        self._finish(spec)

    def complete_error(self, spec: TaskSpec, error: BaseException,
                       allow_retry: bool = True):
        if (allow_retry and not isinstance(error, TaskCancelledError)
                and spec.should_retry(error)):
            with self._lock:
                self._num_retries += 1
            spec.attempt_number += 1
            self._runtime.resubmit_task(spec)
            return
        store = self._runtime.object_store
        if spec.num_returns == STREAMING:
            # Error terminates the stream; readers see it via the
            # sentinel error item.
            err_id = ObjectID.for_return(spec.task_id, 2**20)
            store.put(err_id, RayObject(error=error))
            self._runtime.streaming_manager.report_item(
                spec.return_ids[0], err_id)
            self._runtime.streaming_manager.finish(spec.return_ids[0])
        for oid in spec.return_ids:
            store.put(oid, RayObject(error=error))
        self._finish(spec)

    def _finish(self, spec: TaskSpec):
        # Task is done for good (no further retries): drop the
        # submitted-task references on its arguments.
        self._runtime._release_arg_refs(spec)
        with self._lock:
            self._pending.pop(spec.task_id, None)
            live_returns = 0
            for oid in spec.return_ids:
                if self._runtime.reference_counter.has_reference(oid):
                    live_returns += 1
            if live_returns and spec.function is not None:
                self._lineage[spec.task_id] = spec
                self._lineage_refcount[spec.task_id] = live_returns
        # Release lineage when the last return goes out of scope.  A
        # reconstruction re-finish must not stack a second listener on
        # the same oid (it would double-decrement the refcount).
        for oid in spec.return_ids:
            with self._lock:
                if oid in self._listening:
                    continue
                self._listening.add(oid)
            self._runtime.reference_counter.on_out_of_scope(
                oid, self._on_return_out_of_scope)

    def abandon(self, spec: TaskSpec):
        """Back out a task that was registered but never submitted (the
        caller keeps the exception; no error objects are sealed and the
        never-handed-out return refs are forgotten entirely)."""
        self._runtime._release_arg_refs(spec)
        with self._lock:
            self._pending.pop(spec.task_id, None)
        for oid in spec.return_ids:
            self._runtime.reference_counter.forget_if_unreferenced(oid)

    def _on_return_out_of_scope(self, object_id: ObjectID):
        task_id = object_id.task_id()
        with self._lock:
            self._listening.discard(object_id)
            if task_id in self._lineage_refcount:
                self._lineage_refcount[task_id] -= 1
                if self._lineage_refcount[task_id] <= 0:
                    del self._lineage_refcount[task_id]
                    self._lineage.pop(task_id, None)

    # -- introspection / recovery -------------------------------------------
    def is_pending(self, task_id: TaskID) -> bool:
        with self._lock:
            return task_id in self._pending

    def get_pending_spec(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            return self._pending.get(task_id)

    def lineage_spec(self, object_id: ObjectID) -> Optional[TaskSpec]:
        """Spec of the task that created this object, if pinned."""
        with self._lock:
            return self._lineage.get(object_id.task_id())

    def take_lineage_for_recovery(self, task_id: TaskID
                                  ) -> Optional[TaskSpec]:
        """Pop a finished task's pinned spec to re-execute it (object
        recovery, object_recovery_manager.h:41).  The spec re-enters
        the pending table via ``reregister_for_recovery`` and re-pins
        itself on the next finish."""
        with self._lock:
            spec = self._lineage.pop(task_id, None)
            if spec is not None:
                self._lineage_refcount.pop(task_id, None)
            return spec

    def reregister_for_recovery(self, spec: TaskSpec) -> None:
        """Put a recovered spec back in flight: pending-table entry,
        owned return refs, and submitted-task refs on its args (the
        mirror of what ``_finish`` released)."""
        with self._lock:
            self._pending[spec.task_id] = spec
            self._num_reconstructions += 1
        rc = self._runtime.reference_counter
        for oid in spec.return_ids:
            rc.add_owned_object(oid)
        from .object_ref import ObjectRef

        arg_ids = [a.object_id() for a in spec.args
                   if isinstance(a, ObjectRef)]
        arg_ids += [v.object_id() for v in spec.kwargs.values()
                    if isinstance(v, ObjectRef)]
        rc.add_submitted_task_references(arg_ids)

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def num_lineage_entries(self) -> int:
        with self._lock:
            return len(self._lineage)

    def num_retries(self) -> int:
        with self._lock:
            return self._num_retries

    def num_reconstructions(self) -> int:
        with self._lock:
            return self._num_reconstructions


def _sizeof(value) -> int:
    try:
        import sys

        if hasattr(value, "nbytes"):
            return int(value.nbytes)
        return sys.getsizeof(value)
    except Exception:
        return 0
