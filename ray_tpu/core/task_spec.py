"""Task specifications and function descriptors.

Reference semantics: src/ray/common/task/task_spec.h — an immutable
description of one invocation: function descriptor, argument refs/values,
return count, resource demand, retry policy, scheduling strategy, and the
actor it belongs to (if any).  Specs are retained by the owner for lineage
reconstruction (task_manager.h:219).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

from .ids import ActorID, JobID, ObjectID, TaskID


@dataclass(frozen=True)
class FunctionDescriptor:
    module_name: str
    function_name: str
    class_name: str = ""

    @classmethod
    def from_function(cls, fn: Callable) -> "FunctionDescriptor":
        return cls(getattr(fn, "__module__", "") or "",
                   getattr(fn, "__qualname__", repr(fn)))

    @classmethod
    def from_class(cls, klass: type) -> "FunctionDescriptor":
        return cls(getattr(klass, "__module__", "") or "",
                   "__init__", klass.__qualname__)

    def repr_name(self) -> str:
        if self.class_name:
            return f"{self.class_name}.{self.function_name}"
        return self.function_name


# Scheduling strategies (reference: python/ray/util/scheduling_strategies.py)
@dataclass(frozen=True)
class DefaultSchedulingStrategy:
    pass


@dataclass(frozen=True)
class SpreadSchedulingStrategy:
    pass


@dataclass(frozen=True)
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


@dataclass(frozen=True)
class PlacementGroupSchedulingStrategy:
    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass(frozen=True)
class NodeLabelSchedulingStrategy:
    hard: Dict[str, Any] = field(default_factory=dict)
    soft: Dict[str, Any] = field(default_factory=dict)


SchedulingStrategy = Union[
    DefaultSchedulingStrategy, SpreadSchedulingStrategy,
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy,
    NodeLabelSchedulingStrategy, str, None,
]


def normalize_strategy(strategy: SchedulingStrategy) -> SchedulingStrategy:
    """Map the string spellings the reference API accepts
    ("SPREAD"/"DEFAULT", util/scheduling_strategies.py) onto the
    dataclass forms the dispatchers match on."""
    if isinstance(strategy, str):
        name = strategy.upper()
        if name == "SPREAD":
            return SpreadSchedulingStrategy()
        if name == "DEFAULT":
            return None
        raise ValueError(
            f"unknown scheduling_strategy string {strategy!r} "
            "(expected 'DEFAULT' or 'SPREAD')")
    return strategy

STREAMING = "streaming"


@dataclass
class TaskOptions:
    """Resolved ``.options(...)`` for one submission (remote_function.py)."""

    num_returns: Union[int, str] = 1
    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 3
    retry_exceptions: Union[bool, Sequence[type]] = False
    scheduling_strategy: SchedulingStrategy = None
    name: str = ""
    runtime_env: Optional[dict] = None
    # Run in a pooled worker subprocess (N8 process isolation) instead
    # of inline in the node process.
    isolate: bool = False
    # End-to-end budget in seconds (core/deadlines.py): resolved to an
    # ABSOLUTE deadline at submission; None inherits the submitter's
    # ambient deadline.
    deadline_s: Optional[float] = None
    _metadata: Dict[str, Any] = field(default_factory=dict)

    def resource_demand(self, default_cpus: float = 1.0) -> Dict[str, float]:
        demand = dict(self.resources)
        cpus = self.num_cpus if self.num_cpus is not None else default_cpus
        if cpus:
            demand["CPU"] = cpus
        if self.num_tpus:
            demand["TPU"] = self.num_tpus
        return demand


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    function: Optional[Callable]
    descriptor: FunctionDescriptor
    args: Tuple[Any, ...]
    kwargs: Dict[str, Any]
    num_returns: Union[int, str]
    resources: Dict[str, float]
    max_retries: int
    retry_exceptions: Union[bool, Sequence[type]]
    scheduling_strategy: SchedulingStrategy = None
    name: str = ""
    # Actor linkage
    actor_id: Optional[ActorID] = None
    is_actor_creation: bool = False
    is_actor_task: bool = False
    concurrency_group: str = ""
    # Process isolation (N8): execute in a pooled subprocess.
    isolate: bool = False
    # Ownership / lineage
    parent_task_id: Optional[TaskID] = None
    attempt_number: int = 0
    return_ids: Tuple[ObjectID, ...] = ()
    # Trace propagation (observability/tracing.py): the submitter's
    # trace id + span, carried with the spec across process hops so the
    # span this execution records attaches to the right trace.
    trace_id: Optional[str] = None
    parent_span_id: Optional[str] = None
    # Absolute end-to-end deadline (epoch seconds, core/deadlines.py):
    # carried next to the trace id across every hop; dequeue points
    # shed the spec with DeadlineExceededError once it passes.
    deadline: Optional[float] = None
    # Cluster: nodes that already failed this task (spillback exclusion,
    # reference: normal_task_submitter.cc:455 retry_at_raylet_address).
    _excluded_nodes: Tuple[str, ...] = ()

    def exclude_node(self, node_id: str):
        if node_id not in self._excluded_nodes:
            self._excluded_nodes = self._excluded_nodes + (node_id,)

    def trace_ctx(self) -> Optional[Tuple[str, Optional[str]]]:
        """(trace_id, parent_span_id) for wire propagation, or None."""
        if self.trace_id is None:
            return None
        return (self.trace_id, self.parent_span_id)

    def excluded_nodes(self) -> Tuple[str, ...]:
        return self._excluded_nodes

    def repr_name(self) -> str:
        return self.name or self.descriptor.repr_name()

    def should_retry(self, error: BaseException) -> bool:
        if self.attempt_number >= self.max_retries:
            return False
        # Application errors retry only if retry_exceptions allows
        # (reference: max_retries counts system failures by default;
        # retry_exceptions=True/[...] opts user exceptions in).
        from ..exceptions import (ActorDiedError, NodeDiedError,
                                  OutOfMemoryError, TaskError,
                                  WorkerCrashedError)

        # Unwrap TaskError: execute_task_inline wraps in-task raises,
        # so a WorkerCrashedError from the isolated pool arrives as
        # TaskError(cause=WorkerCrashedError).
        unwrapped = error.cause if isinstance(error, TaskError) else error
        system_failure = isinstance(
            unwrapped, (NodeDiedError, OutOfMemoryError,
                        WorkerCrashedError)) or (
            # An actor dying with its node is a system failure for the
            # CALL; the budget (max_retries = the actor's
            # max_task_retries) gates how many such deaths a call may
            # survive (reference: actor_task_submitter.h:75).
            self.is_actor_task and isinstance(
                unwrapped, ActorDiedError))
        if system_failure:
            return True
        if self.retry_exceptions is True:
            return True
        if self.retry_exceptions:
            cause = error.cause if isinstance(error, TaskError) else error
            return isinstance(cause, tuple(self.retry_exceptions))
        return False
