"""Typed runtime config flag table with env-var override.

Reference semantics: src/ray/common/ray_config.h:60 + ray_config_def.h —
a table of typed flags, each overridable via a ``RAY_<name>`` environment
variable or an explicit ``_system_config`` dict at init time.  Here the
prefix is ``RAY_TPU_`` and the table is a dataclass-like registry; every
process (driver + spawned workers) receives the serialized overrides so
the whole cluster sees one consistent config (ray_config.h:95).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"

_BOOL_TRUE = {"1", "true", "True", "yes", "on"}
_BOOL_FALSE = {"0", "false", "False", "no", "off"}


def _parse(type_: type, raw: str) -> Any:
    if type_ is bool:
        if raw in _BOOL_TRUE:
            return True
        if raw in _BOOL_FALSE:
            return False
        raise ValueError(f"cannot parse bool from {raw!r}")
    if type_ is str:
        return raw
    return type_(raw)


class _Flag:
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name: str, type_: type, default: Any, doc: str):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc


class Config:
    """Registry of typed flags. Resolution order: explicit override >
    environment variable > default."""

    def __init__(self):
        self._flags: Dict[str, _Flag] = {}
        self._overrides: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def define(self, name: str, type_: type, default: Any, doc: str = ""):
        self._flags[name] = _Flag(name, type_, default, doc)

    def get(self, name: str) -> Any:
        flag = self._flags[name]
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
        env = os.environ.get(_ENV_PREFIX + name)
        if env is not None:
            return _parse(flag.type, env)
        return flag.default

    def set(self, name: str, value: Any):
        flag = self._flags[name]
        if not isinstance(value, flag.type):
            value = flag.type(value)
        with self._lock:
            self._overrides[name] = value

    def update(self, system_config: Dict[str, Any]):
        for k, v in system_config.items():
            self.set(k, v)

    def serialize_overrides(self) -> str:
        with self._lock:
            return json.dumps(self._overrides)

    def load_overrides(self, blob: str):
        self.update(json.loads(blob))

    def reset(self):
        with self._lock:
            self._overrides.clear()

    def __getattr__(self, name: str) -> Callable[[], Any]:
        # config.task_retry_delay_ms() style accessors, mirroring
        # RayConfig::instance().flag() in the reference.
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._flags:
            raise AttributeError(f"unknown config flag: {name}")
        return lambda: self.get(name)

    def describe(self) -> Dict[str, Any]:
        return {
            name: {"type": f.type.__name__, "default": f.default, "doc": f.doc}
            for name, f in self._flags.items()
        }


GLOBAL_CONFIG = Config()
_d = GLOBAL_CONFIG.define

# --- core scheduling / tasks ------------------------------------------------
_d("task_retry_delay_ms", int, 0, "Delay before owner-side task resubmission.")
_d("max_pending_lease_requests_per_scheduling_category", int, 10,
   "Parallel lease requests per SchedulingKey (normal_task_submitter.h).")
_d("scheduler_spread_threshold", float, 0.5,
   "Hybrid policy: prefer local node until utilization crosses this.")
_d("num_workers_per_node", int, 0,
   "Worker processes per node; 0 = num_cpus.")
_d("worker_lease_timeout_ms", int, 30_000, "Lease grant timeout.")
_d("actor_creation_timeout_ms", int, 60_000, "Actor readiness timeout.")
_d("max_direct_call_object_size", int, 100 * 1024,
   "Results at or below this inline into the owner's memory store "
   "(reference ray_config_def.h max_direct_call_object_size).")

# --- object store -----------------------------------------------------------
_d("object_store_memory_bytes", int, 2 * 1024**3,
   "Host shared-memory store capacity per node.")
_d("object_spilling_threshold", float, 0.8,
   "Fraction of store capacity that triggers spilling.")
_d("object_spilling_directory", str, "",
   "Directory for spilled objects; empty = <session_dir>/spill.")
_d("object_store_full_delay_ms", int, 100, "Retry delay when store is full.")
_d("object_chunk_bytes", int, 4 * 1024 * 1024,
   "Chunk size for inter-node object pulls (object_buffer_pool.h).")
_d("object_shm_directory", str, "/dev/shm",
   "tmpfs directory for shared-memory primary copies (plasma proper, "
   "store.h:55); empty disables shm re-homing.")
_d("object_shm_min_bytes", int, 1024 * 1024,
   "Primary copies at or above this size are re-homed to shared "
   "memory at seal time; 0 disables.")
_d("object_pull_streams", int, 4,
   "Cap on parallel TCP connections per chunked pull/push.  One socket "
   "serializes all chunks behind one reader thread (~0.8 GB/s "
   "loopback); striping chunks over N sockets multiplies throughput "
   "until memory bandwidth (recv copies release the GIL).  The actual "
   "stream count adapts to payload size (cluster/geometry.py): small "
   "payloads ride one stream, big ones scale up to this cap.")
_d("object_stream_stripe_bytes", int, 16 * 1024 * 1024,
   "Payload bytes per additional transfer stream: a pull/push opens "
   "ceil(total / this) streams, capped at object_pull_streams "
   "(cluster/geometry.py adaptive geometry).")
_d("object_broadcast_fanout", int, 2,
   "Children per node in the push-based broadcast tree "
   "(push_manager.h:30 analogue; depth = log_fanout(n)).")
_d("max_lineage_bytes", int, 100 * 1024 * 1024,
   "Lineage pinned for reconstruction, per owner (task_manager.h:219).")

# --- isolated worker pool (N8) + memory monitor (N22) -----------------------
_d("isolated_pool_prestart", int, 0,
   "Worker subprocesses spawned ahead of demand "
   "(worker_pool.h:216 prestart).")
_d("isolated_pool_max_workers", int, 8,
   "Max concurrent isolated worker subprocesses per node.")
_d("isolated_pool_idle_timeout_s", float, 60.0,
   "Idle pooled workers beyond the prestart count are reaped after "
   "this long (worker_pool.h idle killing).")
_d("memory_usage_threshold", float, 0.95,
   "Node memory fraction that triggers the OOM killer on isolated "
   "workers (ray_config_def.h memory_usage_threshold).")
_d("memory_monitor_refresh_ms", int, 250,
   "Memory watermark poll period; 0 disables the monitor "
   "(memory_monitor.h:52).")

# --- fault tolerance --------------------------------------------------------
_d("health_check_period_ms", int, 1000, "GCS → node health probe period.")
_d("health_check_failure_threshold", int, 5,
   "Missed probes before a node is declared dead.")
_d("task_events_max_buffer_size", int, 10_000,
   "Per-worker buffered task events before flush to GCS.")
_d("gcs_storage", str, "memory", "GCS table storage backend: memory | file.")

# --- chaos / testing (reference: rpc_chaos.h, asio_chaos.h) -----------------
_d("testing_rpc_failure", str, "",
   'Fault injection: "Method=max_failures" drops matching RPCs.')
_d("testing_delay_us", str, "",
   'Fault injection: "Method=min:max" adds random handler delay.')

# --- logging / observability ------------------------------------------------
_d("event_stats", bool, True, "Record per-handler event-loop stats.")
_d("metrics_report_interval_ms", int, 2000, "Metrics push period.")

# --- TPU / mesh -------------------------------------------------------------
_d("tpu_premap_ici_mesh", bool, True,
   "Lay out device meshes along physical ICI torus coordinates.")
_d("default_remat_policy", str, "nothing_saveable",
   "jax.checkpoint policy for train steps built by ray_tpu.train.")
